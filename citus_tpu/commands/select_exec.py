"""SELECT execution machinery above the physical planner: window
functions, DISTINCT ON, derived tables, set operations, CTE scaffolding
(WITH), GROUPING SETS, view/function expansion, and constant selects.

Reference: the coordinator-side combine/query shaping the reference
does in combine_query_planner.c + multi_logical_optimizer.c's master
query, plus cte_inline.c (WITH), setop handling in recursive planning,
and window/distinct paths the reference pushes down when partitioned by
the distribution column.
"""

from __future__ import annotations

from typing import Optional

from citus_tpu.errors import (
    AnalysisError, ExecutionError, UnsupportedFeatureError,
)
from citus_tpu.executor import Result, execute_select
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_sql
from citus_tpu.planner.bind import bind_select
from citus_tpu.schema import Column, Schema

from citus_tpu.cluster import (  # noqa: E402  (loaded post-cluster)
    _eval_const, _infer_column_type, _replace_exprs, _sort_rows,
    _srf_result, _subst_args,
)


def _resolve_window_ref(wc: A.WindowCall, windows: dict,
                        _seen: Optional[set] = None) -> A.WindowCall:
    """Resolve OVER w / OVER (w ...) against the WINDOW clause,
    following PostgreSQL's copy rules: the referencing spec may not
    re-partition, may order only when the base does not, and always
    uses its own frame (the base may not define one when copied);
    OVER w uses the named window verbatim, frame included."""
    if wc.ref_name is None:
        return wc
    if _seen is None:
        _seen = set()
    if wc.ref_name in _seen:
        raise AnalysisError(
            f'circular reference in window "{wc.ref_name}"')
    _seen.add(wc.ref_name)
    base = windows.get(wc.ref_name)
    if base is None:
        raise AnalysisError(f'window "{wc.ref_name}" does not exist')
    if base.ref_name is not None:
        base = _resolve_window_ref(base, windows, _seen)
    if wc.ref_verbatim:
        return A.WindowCall(wc.func, base.partition_by, base.order_by,
                            base.frame)
    if wc.partition_by:
        raise AnalysisError(
            "cannot override PARTITION BY of a named window")
    if wc.order_by and base.order_by:
        raise AnalysisError(
            "cannot override ORDER BY of a named window that has one")
    if base.frame is not None:
        raise AnalysisError(
            "cannot copy a named window that has a frame clause")
    return A.WindowCall(wc.func, base.partition_by,
                        wc.order_by or base.order_by, wc.frame)

def _execute_distinct_on(cl, stmt: A.Select) -> Result:
    """SELECT DISTINCT ON (exprs): keep the first row of each key
    group in ORDER BY order (PostgreSQL semantics — planned as
    Unique over Sort).  The key expressions run as trailing hidden
    outputs of the inner query; deduplication happens on the
    coordinator, then LIMIT/OFFSET apply to the deduplicated rows."""
    import dataclasses as _dc
    on = list(stmt.distinct_on)

    def resolve(e):
        # ordinals and output aliases resolve to their select item
        if isinstance(e, A.Literal) and isinstance(e.value, int) \
                and not isinstance(e.value, bool):
            idx = e.value - 1
            if 0 <= idx < len(stmt.items):
                return stmt.items[idx].expr
        if isinstance(e, A.ColumnRef) and e.table is None:
            for it in stmt.items:
                if it.alias == e.name:
                    return it.expr
        return e

    for i, e in enumerate(on):
        if i < len(stmt.order_by) \
                and resolve(stmt.order_by[i].expr) != resolve(e):
            raise AnalysisError(
                "SELECT DISTINCT ON expressions must match initial "
                "ORDER BY expressions")
    order_by = list(stmt.order_by) \
        or [A.OrderItem(e, True, None) for e in on]
    hidden = [A.SelectItem(resolve(e), f"__distinct_on_{i}")
              for i, e in enumerate(on)]
    inner = _dc.replace(stmt, items=list(stmt.items) + hidden,
                        order_by=order_by, limit=None, offset=None,
                        distinct_on=())
    r = cl._execute_stmt(inner)
    k = len(on)
    seen, rows = set(), []
    for row in r.rows:
        key = row[-k:]
        if key in seen:
            continue
        seen.add(key)
        rows.append(row[:-k])
    if stmt.offset:
        rows = rows[stmt.offset:]
    if stmt.limit is not None:
        rows = rows[:stmt.limit]
    return Result(columns=r.columns[:-k], rows=rows,
                  explain={**(r.explain or {}),
                           "strategy": "distinct_on"},
                  types=r.types[:-k] if r.types else r.types)

def _execute_window(cl, stmt: A.Select) -> Result:
    """Window functions: run the base projection (or grouped
    aggregation) distributed, apply the window pass on the
    coordinator (pull strategy)."""
    import dataclasses

    from citus_tpu.executor.window import AGGS, NAVIGATION, compute_window
    if stmt.distinct:
        raise UnsupportedFeatureError(
            "window functions with DISTINCT not supported yet")
    if stmt.windows or any(isinstance(i.expr, A.WindowCall)
                           and i.expr.ref_name is not None
                           for i in stmt.items):
        import dataclasses
        wmap = dict(stmt.windows)
        stmt = dataclasses.replace(stmt, items=[
            A.SelectItem(_resolve_window_ref(i.expr, wmap)
                         if isinstance(i.expr, A.WindowCall) else i.expr,
                         i.alias)
            for i in stmt.items])
    base_items: list[A.SelectItem] = []

    def base_slot(e: A.Expr) -> int:
        base_items.append(A.SelectItem(e, f"__w{len(base_items)}"))
        return len(base_items) - 1

    def literal_value(a: A.Expr):
        if isinstance(a, A.Literal):
            return a.value
        if isinstance(a, A.UnOp) and a.op == "-" \
                and isinstance(a.operand, A.Literal):
            return -a.operand.value
        raise UnsupportedFeatureError(
            "window function extra arguments must be literals")

    outputs = []  # ("col", slot) | ("win", fn, arg_slots, part, order, frame, params)
    names = []
    for i, item in enumerate(stmt.items):
        e = item.expr
        if isinstance(e, A.WindowCall):
            fn = e.func.name
            if e.func.filter is not None:
                if fn not in AGGS:
                    raise AnalysisError(
                        "FILTER is only allowed for aggregate window "
                        "functions")
                # same CASE desugar as plain aggregates: the window
                # aggregates above skip NULL inputs
                from citus_tpu.planner.bind import rewrite_agg_filter
                e = dataclasses.replace(e, func=rewrite_agg_filter(e.func))
            args = [a for a in e.func.args if not isinstance(a, A.Star)]
            if fn in NAVIGATION:
                arg_slots = [base_slot(args[0])] if args else []
                params = tuple(literal_value(a) for a in args[1:])
            elif fn == "ntile":
                arg_slots = []
                params = tuple(literal_value(a) for a in args[:1])
            else:
                arg_slots = [base_slot(a) for a in args]
                params = ()
            part_slots = [base_slot(p) for p in e.partition_by]
            order_specs = [(base_slot(oe), asc) for oe, asc in e.order_by]
            outputs.append(("win", fn, arg_slots, part_slots, order_specs,
                            e.frame, params))
            names.append(item.alias or fn)
        else:
            outputs.append(("col", base_slot(e)))
            names.append(item.alias or (e.name if isinstance(e, A.ColumnRef)
                                        else f"column{i + 1}"))
    # the base query keeps GROUP BY/HAVING: windows then run over the
    # grouped rows (PostgreSQL semantics — windows after aggregation)
    base = A.Select(base_items, stmt.from_, stmt.where,
                    stmt.group_by, stmt.having)
    def window_pass(rows_in: list) -> list[tuple]:
        """Apply every window spec over one row set -> output rows."""
        n = len(rows_in)
        cols = [[row[j] for row in rows_in] for j in range(len(base_items))]
        out_cols = []
        for spec in outputs:
            if spec[0] == "col":
                out_cols.append(cols[spec[1]])
            else:
                _, fn, arg_slots, part_slots, order_specs, frame, params = spec
                out_cols.append(compute_window(
                    n, fn, [cols[s] for s in arg_slots],
                    [cols[s] for s in part_slots],
                    [(cols[s], asc) for s, asc in order_specs],
                    frame=frame, params=params))
        return [tuple(c[i] for c in out_cols) for i in range(n)]

    strategy = "window:pull"
    if _window_pushdown_eligible(cl, stmt, outputs):
        # every window partitions by the distribution column, so no
        # partition spans shards: the whole window computation runs
        # per shard and results concatenate (reference: pushdown when
        # partitioned by the distribution column, multi_explain/
        # query_pushdown_planning safety proof)
        import dataclasses
        from citus_tpu.planner.physical import plan_select
        bound = bind_select(cl.catalog, base)
        plan = plan_select(cl.catalog, bound,
                           direct_limit=cl.settings.planner.direct_gid_limit)
        rows = []
        for si in plan.shard_indexes:
            shard_plan = dataclasses.replace(plan, shard_indexes=[si])
            shard_rows = execute_select(cl.catalog, bound, cl.settings,
                                        plan=shard_plan).rows
            rows.extend(window_pass(shard_rows))
        strategy = "window:pushdown"
    else:
        rows = window_pass(cl._execute_stmt(base).rows)
    # outer ORDER BY / LIMIT over the final outputs (name or position)
    for oi in reversed(stmt.order_by):
        idx = None
        if isinstance(oi.expr, A.Literal) and isinstance(oi.expr.value, int):
            idx = oi.expr.value - 1
        elif isinstance(oi.expr, A.ColumnRef) and oi.expr.name in names:
            idx = names.index(oi.expr.name)
        if idx is None or not (0 <= idx < len(names)):
            raise AnalysisError(
                "ORDER BY with window functions must reference an output "
                "name or position")
        nf = oi.nulls_first if oi.nulls_first is not None else (not oi.ascending)
        nulls = [x for x in rows if x[idx] is None]
        vals = [x for x in rows if x[idx] is not None]
        vals.sort(key=lambda x, j=idx: x[j], reverse=not oi.ascending)
        rows = (nulls + vals) if nf else (vals + nulls)
    if stmt.offset:
        rows = rows[stmt.offset:]
    if stmt.limit is not None:
        rows = rows[:stmt.limit]
    return Result(columns=names, rows=rows,
                  explain={"strategy": strategy})

def _injective_in_column(e: A.Expr, col: str, alias: str) -> bool:
    """True when ``e`` is an injective function of the column: equal
    outputs imply equal column values, so partitioning by it can
    never group rows from different shards.  Covers the column
    itself and +/- of a constant, * by a nonzero constant, and
    unary minus, composed."""
    if isinstance(e, A.ColumnRef):
        return e.name == col and (e.table is None or e.table == alias)
    if isinstance(e, A.UnOp) and e.op == "-":
        return _injective_in_column(e.operand, col, alias)
    if isinstance(e, A.BinOp) and e.op in ("+", "-", "*"):
        def const_val(x):
            # integers only: float +/× is NOT injective over bigints
            # (rounding collapses distinct inputs at large magnitude)
            if isinstance(x, A.Literal) and isinstance(x.value, int) \
                    and not isinstance(x.value, bool):
                return x.value
            if isinstance(x, A.UnOp) and x.op == "-":
                v = const_val(x.operand)
                return -v if v is not None else None
            return None
        for side, other in ((e.left, e.right), (e.right, e.left)):
            c = const_val(other)
            if c is None:
                continue
            if e.op == "*" and c == 0:
                return False
            if e.op == "-" and side is e.right and other is e.left:
                # const - expr: still injective
                pass
            if _injective_in_column(side, col, alias):
                return True
    return False

def _window_pushdown_eligible(cl, stmt: A.Select, outputs) -> bool:
    """Safe to compute windows per shard: single distributed table,
    no GROUP BY, and every window's PARTITION BY includes the
    distribution column or an injective expression over it (equal
    partition values then imply equal distribution values, and hash
    partitions never span shards)."""
    if stmt.group_by or stmt.having:
        return False
    if not isinstance(stmt.from_, A.TableRef):
        return False
    if not cl.catalog.has_table(stmt.from_.name):
        return False
    t = cl.catalog.table(stmt.from_.name)
    if not t.is_distributed or t.dist_column is None:
        return False
    alias = stmt.from_.alias or stmt.from_.name
    for item in stmt.items:
        e = item.expr
        if not isinstance(e, A.WindowCall):
            continue
        if not any(_injective_in_column(p, t.dist_column, alias)
                   for p in e.partition_by):
            return False
    return True

_CTE_SEQ = [0]

#: intermediate results at/above this row count distribute back out
#: over the mesh instead of staying coordinator-local (reference:
#: RedistributeTaskListResults / distributed_intermediate_results.c)
DISTRIBUTED_INTERMEDIATE_ROWS = 4096

def _schema_from_result(cl, r: Result, *, strict_empty: bool = False):
    """(deduped column names, column types) for materializing a
    query result as a table.  Planner types win; otherwise infer
    from values.  ``strict_empty``: refuse to guess types for an
    empty untyped result (a PERSISTENT table must not silently get
    bigint columns; throwaway intermediates tolerate the default)."""
    names, seen = [], set()
    for i, n in enumerate(r.columns):
        base = n or f"column{i + 1}"
        cand, k = base, 1
        while cand in seen:
            k += 1
            cand = f"{base}_{k}"
        seen.add(cand)
        names.append(cand)
    types = list(r.types) if r.types else [None] * len(names)
    for i, ct_ in enumerate(types):
        if ct_ is None:
            if strict_empty and not r.rows:
                raise UnsupportedFeatureError(
                    f"cannot infer the type of column {names[i]!r} "
                    "from an empty result; create the table "
                    "explicitly and INSERT instead")
            types[i] = _infer_column_type([row[i] for row in r.rows])
    return names, types

def _create_temp_from_result(cl, prefix: str, label: str, r: Result) -> str:
    """Store a query result as an intermediate-result table (the
    read_intermediate_result analog for CTEs / derived tables / set
    operations).  Small results stay local; large ones hash-
    distribute on their first integer-typed column so downstream
    joins and aggregations run sharded."""
    from citus_tpu import types as T
    names, types = _schema_from_result(cl, r)
    _CTE_SEQ[0] += 1
    tmp = f"__{prefix}_{_CTE_SEQ[0]}_{label}"
    cl.catalog.create_table(
        tmp, Schema([Column(cn, ct_) for cn, ct_ in zip(names, types)]))
    if len(r.rows) >= DISTRIBUTED_INTERMEDIATE_ROWS:
        dist_col = next(
            (cn for cn, ct_ in zip(names, types)
             if ct_.is_integer or ct_.kind in (T.DATE,)), None)
        if dist_col is not None:
            cl.catalog.distribute_table(
                tmp, dist_col, cl.settings.sharding.shard_count,
                cl.catalog.active_node_ids())
            cl.catalog.commit()
    if r.rows:
        cl.copy_from(tmp, rows=r.rows)
    return tmp

def _execute_derived(cl, stmt: A.Select) -> Result:
    """Derived tables: execute each FROM-subquery, materialize it as
    an intermediate result, rewrite the FROM item to reference it
    (reference: RecursivelyPlanSubqueryWalker,
    recursive_planning.c:1303)."""
    temps: list[str] = []

    def repl(item):
        if isinstance(item, A.SubqueryRef):
            r = cl._execute_stmt(item.select)
            if item.alias.startswith("__corr1row_") \
                    and "__cnt" in r.columns:
                # decorrelated NON-aggregate scalar subquery: enforce
                # PostgreSQL's runtime rule that it yields at most
                # one row per outer key.  Stricter than PostgreSQL:
                # we check every inner key, including ones no outer
                # row probes — a conservative error, never a silent
                # wrong answer
                ci = r.columns.index("__cnt")
                ni = (r.columns.index("__cntnull")
                      if "__cntnull" in r.columns else None)
                for row in r.rows:
                    eff = row[ci] or 0
                    if ni is not None and (row[ni] or 0) > 0:
                        eff += 1  # NULL is one distinct row
                    if eff > 1:
                        raise AnalysisError(
                            "more than one row returned by a subquery "
                            "used as an expression")
            tmp = _create_temp_from_result(cl, "derived", item.alias, r)
            temps.append(tmp)
            return A.TableRef(tmp, item.alias)
        if isinstance(item, A.FunctionRef):
            r = _srf_result(item.name, item.args, item.alias)
            label = item.alias or item.name
            tmp = _create_temp_from_result(cl, "srf", label, r)
            temps.append(tmp)
            return A.TableRef(tmp, item.alias or item.name)
        if isinstance(item, A.Join):
            return A.Join(repl(item.left), repl(item.right),
                          item.kind, item.condition)
        return item

    try:
        new_stmt = A.Select(stmt.items, repl(stmt.from_), stmt.where,
                            stmt.group_by, stmt.having, stmt.order_by,
                            stmt.limit, stmt.offset, stmt.distinct,
                            stmt.windows)
        return cl._execute_stmt(new_stmt)
    finally:
        for tmp in temps:
            try:
                cl.drop_table(tmp)
            # lint: disable=SWL01 -- temp-table cleanup is best-effort; the cleaner duty removes orphans
            except Exception:
                pass

def _expand_functions_stmt(cl, stmt, depth: int = 0):
    """Inline user SQL functions (expression macros) everywhere in a
    SELECT/set operation — the planning-time analog of delegating a
    distributed function call next to the data
    (function_call_delegation.c)."""
    if depth > 8:
        raise AnalysisError("SQL function expansion too deep (recursive?)")
    fns = cl.catalog.functions

    def rw(e, d):
        if e is None or not isinstance(e, A.Expr):
            return e
        if isinstance(e, A.FuncCall) and e.name in fns:
            spec = fns[e.name]
            if spec.get("kind") == "statement":
                raise AnalysisError(
                    f'{e.name}() is a trigger function and cannot be '
                    "called in an expression")
            if len(e.args) != len(spec["args"]):
                raise AnalysisError(
                    f'{e.name}() expects {len(spec["args"])} arguments')
            if d > 8:
                raise AnalysisError(
                    "SQL function expansion too deep (recursive?)")
            from citus_tpu.planner.parser import Parser as _P
            body = _P(spec["body"]).parse_expr()
            sub = {n: rw(a, d) for n, a in zip(spec["args"], e.args)}
            return rw(_subst_args(body, sub), d + 1)
        if isinstance(e, A.BinOp):
            return A.BinOp(e.op, rw(e.left, d), rw(e.right, d))
        if isinstance(e, A.UnOp):
            return A.UnOp(e.op, rw(e.operand, d))
        if isinstance(e, A.Between):
            return A.Between(rw(e.expr, d), rw(e.lo, d), rw(e.hi, d), e.negated)
        if isinstance(e, A.InList):
            return A.InList(rw(e.expr, d), tuple(rw(i, d) for i in e.items),
                            e.negated)
        if isinstance(e, A.IsNull):
            return A.IsNull(rw(e.expr, d), e.negated)
        if isinstance(e, A.Cast):
            return A.Cast(rw(e.expr, d), e.type_name, e.type_args)
        if isinstance(e, A.CaseExpr):
            return A.CaseExpr(tuple((rw(c, d), rw(v, d)) for c, v in e.whens),
                              rw(e.else_, d) if e.else_ is not None else None)
        if isinstance(e, A.FuncCall):
            import dataclasses
            return dataclasses.replace(
                e, args=tuple(rw(a, d) for a in e.args),
                agg_order=tuple((rw(oe, d), asc)
                                for oe, asc in e.agg_order),
                filter=rw(e.filter, d) if e.filter is not None else None)
        if isinstance(e, A.WindowCall):
            return A.WindowCall(rw(e.func, d) if e.func is not None else None,
                                tuple(rw(p, d) for p in e.partition_by),
                                tuple((rw(oe, d), asc) for oe, asc in e.order_by),
                                e.frame, e.ref_name, e.ref_verbatim)
        return e

    if isinstance(stmt, A.SetOp):
        return A.SetOp(stmt.op, stmt.all,
                       _expand_functions_stmt(cl, stmt.left, depth + 1),
                       _expand_functions_stmt(cl, stmt.right, depth + 1),
                       stmt.order_by, stmt.limit, stmt.offset)
    return A.Select(
        [A.SelectItem(rw(i.expr, 0), i.alias) for i in stmt.items],
        stmt.from_, rw(stmt.where, 0),
        [rw(g, 0) for g in stmt.group_by], rw(stmt.having, 0),
        [A.OrderItem(rw(o.expr, 0), o.ascending, o.nulls_first)
         for o in stmt.order_by],
        stmt.limit, stmt.offset, stmt.distinct,
        tuple((wn, rw(spec, 0)) for wn, spec in stmt.windows),
        tuple(rw(e, 0) for e in stmt.distinct_on))

def _execute_constant_select(cl, stmt: A.Select) -> Result:
    """SELECT without FROM: constant expressions evaluated on the
    coordinator (one row), including scalar subqueries."""
    from citus_tpu.planner.recursive import rewrite_subqueries
    stmt = rewrite_subqueries(stmt, lambda sub: cl._execute_stmt(sub))
    if stmt.group_by or stmt.having or stmt.distinct:
        raise UnsupportedFeatureError(
            "GROUP BY/HAVING/DISTINCT need a FROM clause")
    row, names = [], []
    for i, item in enumerate(stmt.items):
        row.append(_eval_const(item.expr))
        names.append(item.alias or (item.expr.name
                                    if isinstance(item.expr, A.ColumnRef)
                                    else f"column{i + 1}"))
    rows = [tuple(row)]
    if stmt.where is not None:
        if _eval_const(stmt.where) is not True:
            rows = []
    if stmt.offset:
        rows = rows[stmt.offset:]
    if stmt.limit is not None:
        rows = rows[:stmt.limit]
    return Result(columns=names, rows=rows,
                  explain={"strategy": "constant"})

def _expand_views(cl, item):
    """FROM references to views become derived tables over the view's
    stored SELECT (reference: views as distributed objects,
    commands/view.c; execution via recursive planning)."""
    if isinstance(item, A.TableRef) and item.name in cl.catalog.views:
        sel = parse_sql(cl.catalog.views[item.name])[0]
        return A.SubqueryRef(sel, item.alias or item.name)
    if isinstance(item, A.Join):
        left = _expand_views(cl, item.left)
        right = _expand_views(cl, item.right)
        if left is not item.left or right is not item.right:
            return A.Join(left, right, item.kind, item.condition)
    return item

def _execute_grouping_sets(cl, stmt: A.Select, sets) -> Result:
    """ROLLUP/CUBE/GROUPING SETS: one grouped execution per set,
    select items that are grouping expressions of an absent set pad
    to NULL, results concatenate (reference: native grouping-set
    execution; here composed over the standard grouped pipeline)."""
    all_keys = set()
    for s_ in sets:
        all_keys.update(s_)
    names = []
    for i, item in enumerate(stmt.items):
        names.append(item.alias or (item.expr.name
                                    if isinstance(item.expr, A.ColumnRef)
                                    else f"column{i + 1}"))
    rows_all: list[tuple] = []
    types_first = None
    for s_ in sets:
        keep_pos, sub_items = [], []
        grouping_marks = {}  # position -> 0/1 constant for this set
        for i, item in enumerate(stmt.items):
            e = item.expr
            if isinstance(e, A.FuncCall) and e.name == "grouping" \
                    and len(e.args) == 1:
                # GROUPING(col): 1 when the column is rolled up
                # (absent from this set), 0 when grouped by
                grouping_marks[i] = 0 if e.args[0] in s_ else 1
                continue
            if e in all_keys and e not in s_:
                continue  # key absent from this set: pad NULL
            keep_pos.append(i)
            sub_items.append(item)
        # HAVING may reference rolled-up columns: they are NULL in
        # this set (PostgreSQL semantics)
        having = stmt.having
        if having is not None:
            absent = {k for k in all_keys if k not in s_}
            if absent:
                having = _replace_exprs(
                    having, {k: A.Literal(None, "null") for k in absent})
        if not sub_items:
            # only grouping columns selected and this is the empty
            # set: the grand-total group is one all-NULL row
            probe = A.Select([A.SelectItem(
                A.FuncCall("count", (A.Star(),)))],
                stmt.from_, stmt.where, list(s_), having)
            if cl._execute_stmt(probe).rows:
                full = [None] * len(stmt.items)
                for pos, mark in grouping_marks.items():
                    full[pos] = mark
                rows_all.append(tuple(full))
            continue
        sub = A.Select(sub_items, stmt.from_, stmt.where, list(s_),
                       having)
        r = cl._execute_stmt(sub)
        if types_first is None and not any(
                i not in keep_pos for i in range(len(stmt.items))):
            types_first = r.types
        for row in r.rows:
            full = [None] * len(stmt.items)
            for j, pos in enumerate(keep_pos):
                full[pos] = row[j]
            for pos, mark in grouping_marks.items():
                full[pos] = mark
            rows_all.append(tuple(full))
    if stmt.distinct:
        rows_all = list(dict.fromkeys(rows_all))
    rows_all = _sort_rows(rows_all, names, stmt.order_by)
    if stmt.offset:
        rows_all = rows_all[stmt.offset:]
    if stmt.limit is not None:
        rows_all = rows_all[:stmt.limit]
    return Result(columns=names, rows=rows_all, types=types_first,
                  explain={"strategy": "grouping_sets",
                           "sets": len(sets)})

def _execute_setop(cl, stmt: A.SetOp) -> Result:
    """UNION / INTERSECT / EXCEPT [ALL]: execute both sides, combine
    on the coordinator with SQL bag/set semantics (NULLs compare
    equal, like DISTINCT).  Reference: set operations that cannot be
    pushed down run through recursive planning
    (recursive_planning.c:223)."""
    from collections import Counter
    lres = cl._execute_stmt(stmt.left)
    rres = cl._execute_stmt(stmt.right)
    if len(lres.columns) != len(rres.columns):
        raise AnalysisError(
            "each side of a set operation must return the same number "
            "of columns")
    lrows, rrows = list(lres.rows), list(rres.rows)
    if stmt.op == "union":
        rows = lrows + rrows
        if not stmt.all:
            rows = list(dict.fromkeys(rows))
    elif stmt.op == "intersect":
        rc = Counter(rrows)
        if stmt.all:
            rows, used = [], Counter()
            for row in lrows:
                if used[row] < rc.get(row, 0):
                    used[row] += 1
                    rows.append(row)
        else:
            rows = [row for row in dict.fromkeys(lrows) if rc.get(row, 0)]
    else:  # except
        if stmt.all:
            rc = Counter(rrows)
            rows, used = [], Counter()
            for row in lrows:
                if used[row] < rc.get(row, 0):
                    used[row] += 1
                else:
                    rows.append(row)
        else:
            rset = set(rrows)
            rows = [row for row in dict.fromkeys(lrows) if row not in rset]
    rows = _sort_rows(rows, lres.columns, stmt.order_by)
    if stmt.offset:
        rows = rows[stmt.offset:]
    if stmt.limit is not None:
        rows = rows[:stmt.limit]
    return Result(columns=lres.columns, rows=rows,
                  types=lres.types or rres.types,
                  explain={"strategy": f"setop:{stmt.op}"})

def _execute_with(cl, stmt: A.WithSelect) -> Result:
    """Materialize each CTE as a temporary local table (the
    intermediate-result strategy of recursive_planning.c), rewrite
    references in later CTEs and the body, execute, drop."""
    mapping: dict[str, str] = {}
    temps: list[str] = []

    def remap_from(item):
        if isinstance(item, A.TableRef):
            if item.name in mapping:
                return A.TableRef(mapping[item.name], item.alias or item.name)
            return item
        if isinstance(item, A.Join):
            return A.Join(remap_from(item.left), remap_from(item.right),
                          item.kind, item.condition)
        if isinstance(item, A.SubqueryRef):
            return A.SubqueryRef(remap_select(item.select), item.alias)
        return item

    def remap_select(sel):
        import dataclasses
        if isinstance(sel, A.SetOp):
            return A.SetOp(sel.op, sel.all, remap_select(sel.left),
                           remap_select(sel.right), sel.order_by,
                           sel.limit, sel.offset)
        # dataclasses.replace carries every other field (windows,
        # future additions) — positional rebuilds have dropped
        # fields here before
        return dataclasses.replace(sel, from_=remap_from(sel.from_))

    try:
        for name, sel in stmt.ctes:
            from citus_tpu.cluster import _from_relations
            if stmt.recursive and name in _from_relations(sel):
                r = _iterate_recursive_cte(cl, name, sel, remap_select,
                                           stmt.cte_cols.get(name))
            else:
                r = cl._execute_stmt(remap_select(sel))
                cols = stmt.cte_cols.get(name)
                if cols is not None:
                    if len(cols) != len(r.columns):
                        raise AnalysisError(
                            f'CTE "{name}" has {len(r.columns)} columns, '
                            f"{len(cols)} aliases given")
                    r = Result(columns=list(cols), rows=r.rows,
                               types=r.types)
            tmp = _create_temp_from_result(cl, "cte", name, r)
            mapping[name] = tmp
            temps.append(tmp)
        body = remap_select(stmt.body)
        return cl._execute_stmt(body)
    finally:
        for tmp in temps:
            try:
                cl.drop_table(tmp)
            # lint: disable=SWL01 -- temp-table cleanup is best-effort; the cleaner duty removes orphans
            except Exception:
                pass


#: safety caps for WITH RECURSIVE (the reference relies on PostgreSQL's
#: executor, which iterates unboundedly; a runaway recursion here would
#: eat the coordinator, so both depth and total rows are capped)
RECURSIVE_MAX_ITERATIONS = 500
RECURSIVE_MAX_ROWS = 1_000_000


def _iterate_recursive_cte(cl, name: str, sel, remap_select, cols):
    """WITH RECURSIVE iteration, coordinator-materialized: the CTE must
    be ``base UNION [ALL] recursive_term``; each round the recursive
    term runs with the CTE name bound to the PREVIOUS round's rows (the
    PostgreSQL working-table semantics), until a round yields nothing
    new.  Reference: recursive_planning.c:1175-1181 supports recursive
    CTEs through exactly this materialize-and-iterate shape."""
    from citus_tpu.cluster import _from_relations
    if not (isinstance(sel, A.SetOp) and sel.op == "union"):
        raise UnsupportedFeatureError(
            "a recursive CTE must be 'base UNION [ALL] recursive-term'")
    base, rec = sel.left, sel.right
    if name in _from_relations(base):
        raise UnsupportedFeatureError(
            "the recursive reference must be in the second UNION arm")
    dedup = not sel.all  # UNION distinct: drop already-seen rows
    base_r = cl._execute_stmt(remap_select(base))
    out_cols = list(cols) if cols is not None else list(base_r.columns)
    if cols is not None and len(cols) != len(base_r.columns):
        raise AnalysisError(
            f'CTE "{name}" has {len(base_r.columns)} columns, '
            f"{len(cols)} aliases given")
    seen = set(base_r.rows) if dedup else None
    working = list(dict.fromkeys(base_r.rows)) if dedup else list(base_r.rows)
    result = list(working)
    iterations = 0
    while working:
        iterations += 1
        if iterations > RECURSIVE_MAX_ITERATIONS:
            raise ExecutionError(
                f"recursive CTE {name!r} exceeded "
                f"{RECURSIVE_MAX_ITERATIONS} iterations")
        wr = Result(columns=out_cols, rows=working, types=base_r.types)
        wtmp = _create_temp_from_result(cl, "rcte", name, wr)
        try:
            import dataclasses as _dc

            def bind_working(item):
                if isinstance(item, A.TableRef):
                    if item.name == name:
                        return A.TableRef(wtmp, item.alias or name)
                    return item
                if isinstance(item, A.Join):
                    return _dc.replace(item, left=bind_working(item.left),
                                       right=bind_working(item.right))
                if isinstance(item, A.SubqueryRef):
                    return _dc.replace(item, select=_dc.replace(
                        item.select, from_=bind_working(item.select.from_)))
                return item

            step = remap_select(rec)
            step = _dc.replace(step, from_=bind_working(step.from_))
            rr = cl._execute_stmt(step)
        finally:
            try:
                cl.drop_table(wtmp)
            # lint: disable=SWL01 -- temp-table cleanup is best-effort; the cleaner duty removes orphans
            except Exception:
                pass
        fresh = []
        for row in rr.rows:
            if dedup:
                if row in seen:
                    continue
                seen.add(row)
            fresh.append(row)
        result.extend(fresh)
        if len(result) > RECURSIVE_MAX_ROWS:
            raise ExecutionError(
                f"recursive CTE {name!r} exceeded {RECURSIVE_MAX_ROWS} rows")
        working = fresh
    return Result(columns=out_cols, rows=result, types=base_r.types)


def _execute_unnest(cl, stmt):
    """SELECT ... unnest(arr_expr) ... FROM ...: run the query with the
    array expression in the unnest's place, then explode each row once
    per element, repeating the other output columns (PostgreSQL's
    SRF-in-target-list expansion for a single SRF).

    Reference: unnest(anyarray); multiple SRFs in one target list (PG's
    lock-step expansion) are not supported."""
    import dataclasses

    srf_idx = [i for i, it in enumerate(stmt.items)
               if isinstance(it.expr, A.FuncCall) and it.expr.name == "unnest"]
    if len(srf_idx) != 1:
        raise UnsupportedFeatureError(
            "only one unnest() per target list is supported")
    i = srf_idx[0]
    call = stmt.items[i].expr
    if len(call.args) != 1:
        raise AnalysisError("unnest(array) expects one argument")
    if stmt.group_by or stmt.having or stmt.distinct:
        raise UnsupportedFeatureError(
            "unnest() cannot be combined with GROUP BY/HAVING/DISTINCT")
    inner_items = list(stmt.items)
    inner_items[i] = A.SelectItem(call.args[0],
                                  stmt.items[i].alias or "unnest")
    inner = dataclasses.replace(stmt, items=inner_items,
                                order_by=[], limit=None, offset=None)
    r = cl._execute_stmt(inner)
    out_rows = []
    for row in r.rows:
        arr = row[i]
        if arr is None:
            continue  # PG: NULL array contributes no rows
        if not isinstance(arr, (list, tuple)):
            raise AnalysisError(
                f"unnest requires an array column (got {type(arr).__name__})")
        for v in arr:
            out_rows.append(row[:i] + (v,) + row[i + 1:])
    cols = list(r.columns)
    cols[i] = stmt.items[i].alias or "unnest"
    from citus_tpu.cluster import _sort_rows
    if stmt.order_by:
        out_rows = _sort_rows(out_rows, cols, stmt.order_by)
    if stmt.offset:
        out_rows = out_rows[stmt.offset:]
    if stmt.limit is not None:
        out_rows = out_rows[:stmt.limit]
    return Result(columns=cols, rows=out_rows)
