"""SET/SHOW (GUC analog), ANALYZE, REINDEX, RETURNING evaluation, and
extended-statistics ndistinct computation.

Reference: the ~139 citus.* GUCs (shared_library_init.c:980+) with PG
unit parsing and transactional SET rollback; commands/vacuum.c ANALYZE;
commands/index.c REINDEX.
"""

from __future__ import annotations

from typing import Optional

from citus_tpu.errors import CatalogError
from citus_tpu.executor import Result
from citus_tpu.planner import ast as A

from citus_tpu.cluster import _eval_const, _expand_returning_items  # noqa: E402


def _remote_task_mode(v) -> str:
    """citus.remote_task_execution = push | pull | auto."""
    s = str(v).lower()
    if s not in ("push", "pull", "auto"):
        raise ValueError(s)
    return s


def _autopilot_mode(v) -> str:
    """citus.autopilot = off | observe | on.  The SET parser coerces
    bare on/off to booleans before coercion sees them."""
    if isinstance(v, bool):
        return "on" if v else "off"
    s = str(v).lower()
    if s not in ("off", "observe", "on"):
        raise ValueError(s)
    return s


def _wire_format(v) -> str:
    """citus.wire_format = frame | npz (net/data_plane.py codecs)."""
    s = str(v).lower()
    if s not in ("frame", "npz"):
        raise ValueError(s)
    return s


def _plan_cache_mode(v) -> str:
    """citus.plan_cache_mode = auto | force_generic | force_custom
    (reference: the plancache.c GUC of the same name)."""
    s = str(v).lower()
    if s not in ("auto", "force_generic", "force_custom"):
        raise ValueError(s)
    return s


def _window_ms(v) -> float:
    """citus.megabatch_window_ms = <ms> | auto (stored as -1)."""
    if str(v).lower() == "auto":
        return -1.0
    return float(v)


def _hash_slots(v) -> int:
    """citus.hash_agg_slots = <slots> | auto (stored as 0: sized from
    catalog row-count stats at execution)."""
    if str(v).lower() == "auto":
        return 0
    n = int(v)
    if n < 0:
        raise ValueError(v)
    return n


def _sample_rate(v) -> float:
    """citus.trace_sample_rate = 0.0 .. 1.0."""
    f = float(v)
    if not 0.0 <= f <= 1.0:
        raise ValueError(v)
    return f


def _percentile_backend(v) -> str:
    """citus.percentile_backend = ddsketch | tdigest (the sketch kind
    approx_percentile rollup columns store, rollup/sketches.py)."""
    s = str(v).lower()
    if s not in ("ddsketch", "tdigest"):
        raise ValueError(s)
    return s


def _compute_ndistinct(cl, table: str, columns: list) -> int:
    """count(DISTINCT (cols)) — the extended-statistics ndistinct."""
    sel = A.Select(
        [A.SelectItem(A.FuncCall("count", (A.Star(),)))],
        A.SubqueryRef(A.Select(
            [A.SelectItem(A.ColumnRef(c)) for c in columns],
            A.TableRef(table), distinct=True), "d"))
    return int(cl._execute_stmt(sel).rows[0][0])

#: SET/SHOW surface: GUC name -> (settings section, field, coercion)
#: (reference: the citus.* GUCs, shared_library_init.c:980+).
#: Settings apply to this Cluster handle (every session of it).
_GUCS = {
    "citus.task_executor_backend": ("executor", "task_executor_backend", str),
    "citus.max_shared_pool_size": ("executor", "max_shared_pool_size", int),
    # per-node remote-task RPC window cap (slow-start ramp target,
    # executor/pipeline.py); formerly aliased the device in-flight
    # window, which now has its own name below
    "citus.max_adaptive_executor_pool_size": ("executor", "max_adaptive_pool_size", int),
    "citus.max_tasks_in_flight": ("executor", "max_tasks_in_flight", int),
    # host read-ahead queue depth for the decode thread; 0 = inline
    "citus.executor_prefetch_depth": ("executor", "executor_prefetch_depth", int),
    # native stripe read+decompress pool width; 0 = auto
    # (min(8, cpu_count), storage/reader.py)
    "citus.decode_threads": ("executor", "decode_threads", int),
    "citus.use_secondary_nodes": ("executor", "use_secondary_nodes", "secondary"),
    "citus.remote_task_execution": ("executor", "remote_task_execution", _remote_task_mode),
    # wire codec for execute_task results / placement bundles: the
    # zero-copy columnar frame (default) or the legacy npz container
    "citus.wire_format": ("executor", "wire_format", _wire_format),
    # query-family compile amortization (executor/kernel_cache.py,
    # planner/auto_param.py)
    "citus.plan_cache_mode": ("planner", "plan_cache_mode", _plan_cache_mode),
    "citus.kernel_cache_size": ("executor", "kernel_cache_size", int),
    "citus.jit_cache_dir": ("executor", "jit_cache_dir", str),
    # same-family query coalescing (executor/megabatch.py): dispatch
    # window (ms; 0 = off, byte-identical serial path) and per-batch
    # occupancy bound
    "citus.megabatch_window_ms": ("executor", "megabatch_window_ms", _window_ms),
    "citus.megabatch_max_size": ("executor", "megabatch_max_size", int),
    # multi-tenant admission defaults (workload/scheduler.py): fair-
    # share weight for unregistered tenants, per-tenant queue bound
    # (0 = unbounded) and sustained-QPS token bucket (0 = unlimited)
    "citus.tenant_default_weight": ("workload", "tenant_default_weight", float),
    "citus.tenant_queue_depth": ("workload", "tenant_queue_depth", int),
    "citus.tenant_rate_limit_qps": ("workload", "tenant_rate_limit_qps", float),
    # priority class for tenants without an explicit class (the
    # two-level stride tree's fallback node, workload/scheduler.py)
    "citus.tenant_default_priority_class": ("workload",
                                            "tenant_default_priority_class",
                                            str),
    # multi-coordinator metadata sync (metadata/sync.py): background
    # pull-on-mismatch cadence (ms; 0 = loop off, sync still runs at
    # invalidation + citus_sync_metadata()) and the incremental-sync
    # master switch (off = full-document fetch per invalidation)
    "citus.metadata_sync_interval_ms": ("metadata",
                                        "metadata_sync_interval_ms",
                                        float),
    "citus.enable_metadata_sync": ("metadata", "enable_metadata_sync",
                                   "bool"),
    # distributed tracing (observability/): span-tree sampling rate,
    # slow-query force-capture threshold (ms; -1 off), Chrome-trace
    # export directory ("" off)
    "citus.trace_sample_rate": ("observability", "trace_sample_rate", _sample_rate),
    "citus.log_min_duration_ms": ("observability", "log_min_duration_ms", float),
    "citus.trace_export_dir": ("observability", "trace_export_dir", str),
    "citus.stat_fanout_timeout_s": ("observability", "stat_fanout_timeout_s",
                                    float),
    # cluster flight recorder (observability/flight_recorder.py):
    # background sampling cadence (ms; 0 = recorder off) and on-disk
    # segment retention (seconds)
    "citus.flight_recorder_interval_ms": ("observability",
                                          "flight_recorder_interval_ms",
                                          float),
    "citus.flight_recorder_retention_s": ("observability",
                                          "flight_recorder_retention_s",
                                          float),
    # autopilot control loop (services/autopilot.py): mode switch plus
    # its hysteresis knobs — evaluation cadence, consecutive-tick
    # sustain requirement, post-action cooldown, and the greedy
    # balance trigger threshold
    "citus.autopilot": ("autopilot", "mode", _autopilot_mode),
    "citus.autopilot_interval_s": ("autopilot", "interval_s", float),
    "citus.autopilot_sustain_ticks": ("autopilot", "sustain_ticks", int),
    "citus.autopilot_cooldown_s": ("autopilot", "cooldown_s", float),
    "citus.autopilot_threshold": ("autopilot", "threshold", float),
    # continuous aggregation (rollup/): refresh-loop cadence (ms; 0 =
    # loop off, refresh via citus_refresh_rollups()), percentile sketch
    # backend for NEW rollups, and the per-batch source-row bound
    "citus.rollup_refresh_interval_ms": ("rollup",
                                         "rollup_refresh_interval_ms",
                                         float),
    "citus.percentile_backend": ("rollup", "percentile_backend",
                                 _percentile_backend),
    "citus.rollup_max_batch_rows": ("rollup", "rollup_max_batch_rows",
                                    int),
    "citus.enable_rollup_routing": ("rollup", "enable_rollup_routing",
                                    "bool"),
    "citus.enable_repartition_joins": ("planner", "enable_repartition_joins", "bool"),
    "citus.shard_count": ("sharding", "shard_count", int),
    "citus.shard_replication_factor": ("sharding", "shard_replication_factor", int),
    # non-blocking shard moves (operations/shard_transfer.py): lag bar
    # the catch-up loop must get under before taking the write lock,
    # the bound on catch-up rounds, and whether the source placement
    # drop is deferred to the cleaner or done inline after the flip
    "citus.shard_move_catchup_threshold": ("sharding", "shard_move_catchup_threshold", int),
    "citus.shard_move_max_catchup_rounds": ("sharding", "shard_move_max_catchup_rounds", int),
    "citus.defer_drop_after_shard_move": ("sharding", "defer_drop_after_shard_move", "bool"),
    "citus.enable_change_data_capture": (None, "enable_change_data_capture", "bool"),
    "citus.distributed_deadlock_detection_interval": (None, "deadlock_detection_interval_s", float),
    # every settings field the code reads is SET/SHOW-reachable
    # (cituslint GUC01): batch floor below which shards merge into one
    # device dispatch, router fast-path shard cap, GROUP BY hash-slot
    # budget, repartition-join fanout, and the maintenance/authority
    # daemon knobs
    "citus.executor_min_batch_rows": ("executor", "min_batch_rows", int),
    "citus.direct_gid_limit": ("planner", "direct_gid_limit", int),
    "citus.hash_agg_slots": ("planner", "hash_agg_slots", _hash_slots),
    "citus.repartition_bucket_count_per_device": ("planner", "repartition_bucket_count_per_device", int),
    "citus.start_maintenance_daemon": (None, "start_maintenance_daemon", "bool"),
    "citus.authority_watch_interval": (None, "authority_watch_interval_s", float),
    # PostgreSQL spelling: bare numbers are MILLISECONDS; unit
    # suffixes ('3s', '500ms') accepted
    "lock_timeout": ("executor", "lock_timeout_s", "ms_duration"),
}

def _guc_key(cl, name: str) -> str:
    name = name.lower()
    if name in _GUCS:
        return name
    if f"citus.{name}" in _GUCS:
        return f"citus.{name}"
    raise CatalogError(f'unrecognized configuration parameter "{name}"')

def _execute_set(cl, stmt: A.SetConfig) -> Result:
    import dataclasses as _dc
    key = _guc_key(cl, stmt.name)
    section, field_, coerce = _GUCS[key]
    v = stmt.value
    if coerce == "bool":
        if not isinstance(v, bool):
            s = str(v).lower()
            if s in ("true", "on", "1", "yes"):
                v = True
            elif s in ("false", "off", "0", "no"):
                v = False
            else:
                raise CatalogError(
                    f'parameter "{stmt.name}" requires a Boolean '
                    f"value (got {stmt.value!r})")
    elif coerce == "secondary":
        # PostgreSQL spelling: citus.use_secondary_nodes = always|never
        if isinstance(v, bool):
            pass
        elif str(v).lower() in ("always", "never"):
            v = str(v).lower() == "always"
        else:
            raise CatalogError(
                f'invalid value for parameter "{stmt.name}": '
                f"{stmt.value!r} (expected always or never)")
    elif coerce == "ms_duration":
        # bare numbers are milliseconds (PostgreSQL); 's'/'ms'
        # suffixes accepted
        s = str(v).strip().lower()
        try:
            if s.endswith("ms"):
                v = float(s[:-2]) / 1000.0
            elif s.endswith("s"):
                v = float(s[:-1])
            else:
                v = float(s) / 1000.0
        except ValueError:
            raise CatalogError(
                f'invalid value for parameter "{stmt.name}": '
                f"{stmt.value!r}")
    else:
        try:
            v = coerce(v)
        except (TypeError, ValueError):
            raise CatalogError(
                f'invalid value for parameter "{stmt.name}": {stmt.value!r}')
    from citus_tpu.storage.overlay import current_overlay
    txn = current_overlay()
    if txn is not None:
        # PostgreSQL: a non-LOCAL SET is undone if the transaction
        # aborts
        prev_settings, prev_cdc = cl.settings, cl.cdc.enabled

        def _restore(prev_settings=prev_settings, prev_cdc=prev_cdc):
            cl.settings = prev_settings
            cl.cdc.enabled = prev_cdc
            cl._plan_cache.clear()
        txn.on_rollback.append(_restore)
    if section is None:
        cl.settings = _dc.replace(cl.settings, **{field_: v})
    else:
        sec = _dc.replace(getattr(cl.settings, section), **{field_: v})
        cl.settings = _dc.replace(cl.settings, **{section: sec})
    if key == "citus.enable_change_data_capture":
        cl.cdc.enabled = bool(v)
    elif key == "citus.kernel_cache_size":
        from citus_tpu.executor.kernel_cache import GLOBAL_KERNELS
        GLOBAL_KERNELS.set_capacity(int(v))
    elif key == "citus.decode_threads":
        from citus_tpu.storage.reader import set_decode_threads
        set_decode_threads(int(v))
    elif key == "citus.jit_cache_dir":
        from citus_tpu.executor.kernel_cache import configure_persistent_cache
        configure_persistent_cache(v)
    elif key == "citus.flight_recorder_interval_ms":
        cl.flight_recorder.apply()  # start/stop the sampler to match
    elif key == "citus.rollup_refresh_interval_ms":
        cl.rollup_manager.apply()  # start/stop the refresh loop
    elif key in ("citus.metadata_sync_interval_ms",
                 "citus.enable_metadata_sync"):
        cl.metadata_sync.apply()  # start/stop the sync loop to match
    cl._plan_cache.clear()  # backend/knob changes invalidate plans
    return Result(columns=[], rows=[])

def _guc_value(cl, key: str) -> str:
    section, field_, coerce = _GUCS[key]
    v = getattr(cl.settings, field_) if section is None \
        else getattr(getattr(cl.settings, section), field_)
    if coerce == "secondary":
        return "always" if v else "never"
    if isinstance(v, bool):
        return "on" if v else "off"  # PostgreSQL boolean rendering
    if coerce == "ms_duration":
        return f"{v * 1000:g}ms"
    return str(v)

def _execute_show(cl, stmt: A.ShowConfig) -> Result:
    if stmt.name.lower() == "citus.metrics":
        # SHOW citus.metrics: the Prometheus text exposition, one row
        # per line (scripts/metrics_exporter.py serves the same text)
        from citus_tpu.observability.export import prometheus_text
        return Result(columns=["metrics"],
                      rows=[(line,) for line in
                            prometheus_text(cl).splitlines()])
    if stmt.name == "all":
        rows = [(k, _guc_value(cl, k)) for k in sorted(_GUCS)]
        return Result(columns=["name", "setting"], rows=rows)
    key = _guc_key(cl, stmt.name)
    return Result(columns=[stmt.name], rows=[(_guc_value(cl, key),)])

def _execute_analyze(cl, table: Optional[str]) -> Result:
    """ANALYZE [table]: recompute extended-statistics ndistinct
    (column min/max stats are always skip-list-live here, so there
    is no per-column histogram pass to run)."""
    if table is not None:
        cl.catalog.table(table)  # PostgreSQL: unknown relation errors
    refreshed = 0
    for name, st in cl.catalog.statistics.items():
        if table is not None and st["table"] != table:
            continue
        if not cl.catalog.has_table(st["table"]):
            continue
        st["ndistinct"] = _compute_ndistinct(cl, st["table"],
                                                  st["columns"])
        refreshed += 1
    if refreshed:
        cl.catalog.commit()
    return Result(columns=[], rows=[],
                  explain={"statistics_refreshed": refreshed})

def _execute_reindex(cl, stmt: A.Reindex) -> Result:
    """REINDEX INDEX name | REINDEX TABLE name: rebuild segment
    files from the stripe data (recovers from lost/corrupted
    segments; a missing segment is only a slow path, never wrong)."""
    from citus_tpu.storage.index import backfill_index
    from citus_tpu.transaction.locks import EXCLUSIVE
    if stmt.kind == "index":
        t, ix = cl._find_index(stmt.name)
        if ix is None:
            raise CatalogError(f'index "{stmt.name}" does not exist')
        targets = [(t, [ix["column"]])]
    else:
        t = cl.catalog.table(stmt.name)
        if t.is_partitioned:
            targets = [(p, p.index_columns)
                       for p in cl.catalog.partitions_of(t.name)
                       if p.indexes]
        else:
            targets = [(t, t.index_columns)] if t.indexes else []
    rebuilt = 0
    for tt, cols in targets:
        with cl._write_lock(tt, EXCLUSIVE):
            for col in cols:
                cl._drop_index_segments(tt, col)
            rebuilt += backfill_index(cl.catalog, tt, list(cols))
            tt.version += 1
    if targets:
        cl.catalog.ddl_epoch += 1
        cl.catalog.commit()
        for tt, _cols in targets:
            cl._plan_cache.invalidate_table(tt.name)
    return Result(columns=[], rows=[],
                  explain={"segments_rebuilt": rebuilt})

def _returning_result(cl, table_name, where, items, subst=None):
    """Evaluate a RETURNING clause as a distributed SELECT over the
    affected rows (pre-image WHERE); for UPDATE, assignment
    expressions are substituted into the items so the NEW values are
    returned (reference: adaptive_executor.c DML RETURNING tuples)."""
    t = cl.catalog.table(table_name)
    expanded = _expand_returning_items(t, items, subst)
    # constant items (e.g. SET c = 'z' substituted into RETURNING c)
    # cannot ride the distributed select: fold them on the host and
    # splice one copy per affected row
    consts, sel_items = {}, []
    for idx, (e, alias) in enumerate(expanded):
        try:
            consts[idx] = _eval_const(e)
        except Exception:
            sel_items.append((idx, A.SelectItem(e, alias)))
    if sel_items:
        inner = cl._execute_stmt(A.Select(
            [si for _, si in sel_items], A.TableRef(table_name), where))
        nrows, inner_rows = len(inner.rows), inner.rows
    else:
        cnt = A.Select([A.SelectItem(A.FuncCall("count", (A.Star(),)))],
                       A.TableRef(table_name), where)
        nrows = int(cl._execute_stmt(cnt).rows[0][0] or 0)
        inner_rows = [()] * nrows
    rows = []
    for r in inner_rows:
        full, j = [None] * len(expanded), 0
        for idx in range(len(expanded)):
            if idx in consts:
                full[idx] = consts[idx]
            else:
                full[idx] = r[j]
                j += 1
        rows.append(tuple(full))
    return Result(columns=[a for _, a in expanded], rows=rows)
