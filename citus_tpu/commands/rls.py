"""Row-level security, triggers, and privilege checks.

Reference: commands/policy.c (RLS policies), commands/trigger.c,
commands/grant.c / standard PostgreSQL ACL checks; policies rewrite the
statement tree before planning (the planner-level USING/CHECK
injection PostgreSQL does in the rewriter).
"""

from __future__ import annotations

from typing import Optional

from citus_tpu.errors import (
    AnalysisError, ExecutionError, UnsupportedFeatureError,
)
from citus_tpu.executor import Result
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_sql

from citus_tpu.cluster import _eval_const, _subst_args  # noqa: E402


def _policy_predicate(cl, role: str, table: str, cmd: str,
                      kind: str = "using") -> Optional[A.Expr]:
    """RLS predicate for (role, table, command): None when RLS is
    off for the table; FALSE when enabled with no applicable policy
    (default deny); else the OR of applicable policies' expressions
    (permissive policies, PostgreSQL default).  ``kind`` selects
    USING or WITH CHECK (check falls back to using, as PG does)."""
    if not cl.catalog.rls.get(table):
        return None
    texts = []
    for p in cl.catalog.policies.get(table, ()):
        if p["cmd"] not in ("all", cmd):
            continue
        if "public" not in p["roles"] and role not in p["roles"]:
            continue
        text = p.get(kind) or (p.get("using") if kind == "check" else None)
        if text:
            texts.append(text)
    if not texts:
        return A.Literal(False, "bool")
    from citus_tpu.planner.parser import Parser as _P
    cache = getattr(cl, "_policy_expr_cache", None)
    if cache is None:
        cache = cl._policy_expr_cache = {}
    exprs = []
    for t in texts:
        parsed = cache.get(t)
        if parsed is None:
            parsed = cache[t] = _P(t).parse_expr()
        exprs.append(parsed)
    out = exprs[0]
    for e in exprs[1:]:
        out = A.BinOp("or", out, e)
    return out

def _apply_rls(cl, role: str, stmt: A.Statement):
    """Row-level security rewrite for a non-superuser role ->
    (statement, changed).  Every table reference of an RLS-enabled
    table — in FROM (incl. joins/derived tables), set operations,
    CTEs, and expression subqueries (scalar/IN/EXISTS) — wraps in a
    policy-filtered derived table; UPDATE/DELETE additionally AND
    the predicate into WHERE and enforce WITH CHECK on assignments;
    INSERT VALUES rows evaluate WITH CHECK per row (reference:
    commands/policy.c; superuser role=None bypasses, like table
    owners in PG)."""
    import dataclasses
    changed = [False]
    EMPTY = frozenset()

    def rew_from(item, shadow):
        if isinstance(item, A.TableRef):
            if item.name in shadow:
                return item  # resolves to a CTE, not the base table
            if not cl.catalog.has_table(item.name):
                return item
            f = _policy_predicate(cl, role, item.name, "select")
            if f is None:
                return item
            changed[0] = True
            sel = A.Select([A.SelectItem(A.Star())],
                           A.TableRef(item.name), f)
            return A.SubqueryRef(sel,
                                 item.alias or item.name.split(".")[-1])
        if isinstance(item, A.Join):
            return A.Join(rew_from(item.left, shadow),
                          rew_from(item.right, shadow),
                          item.kind, item.condition)
        if isinstance(item, A.SubqueryRef):
            return A.SubqueryRef(rew_stmt(item.select, shadow),
                                 item.alias)
        return item

    def rew_expr(e, shadow):
        if e is None or not isinstance(e, A.Expr):
            return e
        if isinstance(e, A.Subquery):
            return A.Subquery(rew_stmt(e.select, shadow))
        if isinstance(e, A.Exists):
            return A.Exists(rew_stmt(e.select, shadow))
        if isinstance(e, A.BinOp):
            return A.BinOp(e.op, rew_expr(e.left, shadow),
                           rew_expr(e.right, shadow))
        if isinstance(e, A.UnOp):
            return A.UnOp(e.op, rew_expr(e.operand, shadow))
        if isinstance(e, A.Between):
            return A.Between(rew_expr(e.expr, shadow),
                             rew_expr(e.lo, shadow),
                             rew_expr(e.hi, shadow), e.negated)
        if isinstance(e, A.InList):
            return A.InList(rew_expr(e.expr, shadow),
                            tuple(rew_expr(i, shadow) for i in e.items),
                            e.negated)
        if isinstance(e, A.IsNull):
            return A.IsNull(rew_expr(e.expr, shadow), e.negated)
        if isinstance(e, A.Cast):
            return A.Cast(rew_expr(e.expr, shadow), e.type_name,
                          e.type_args)
        if isinstance(e, A.CaseExpr):
            return A.CaseExpr(
                tuple((rew_expr(c, shadow), rew_expr(v, shadow))
                      for c, v in e.whens),
                rew_expr(e.else_, shadow) if e.else_ is not None
                else None)
        if isinstance(e, A.FuncCall):
            import dataclasses
            return dataclasses.replace(
                e, args=tuple(rew_expr(a, shadow) for a in e.args),
                agg_order=tuple((rew_expr(oe, shadow), asc)
                                for oe, asc in e.agg_order),
                filter=rew_expr(e.filter, shadow)
                if e.filter is not None else None)
        if isinstance(e, A.WindowCall):
            return A.WindowCall(
                rew_expr(e.func, shadow) if e.func is not None else None,
                tuple(rew_expr(p, shadow) for p in e.partition_by),
                tuple((rew_expr(oe, shadow), asc)
                      for oe, asc in e.order_by),
                e.frame, e.ref_name, e.ref_verbatim)
        return e

    def rew_stmt(s, shadow):
        if isinstance(s, A.SetOp):
            return dataclasses.replace(s, left=rew_stmt(s.left, shadow),
                                       right=rew_stmt(s.right, shadow))
        if isinstance(s, A.WithSelect):
            # a CTE's definition may reference only EARLIER CTE
            # names; later refs resolve to the base relations
            seen = set(shadow)
            new_ctes = []
            for n, sel in s.ctes:
                new_ctes.append((n, rew_stmt(sel, frozenset(seen))))
                seen.add(n)
            return A.WithSelect(new_ctes,
                                rew_stmt(s.body, frozenset(seen)),
                                s.recursive, s.cte_cols)
        if not isinstance(s, A.Select):
            return s
        return dataclasses.replace(
            s,
            items=[A.SelectItem(rew_expr(i.expr, shadow), i.alias)
                   for i in s.items],
            from_=rew_from(s.from_, shadow) if s.from_ is not None
            else None,
            where=rew_expr(s.where, shadow),
            group_by=[rew_expr(g, shadow) for g in s.group_by],
            having=rew_expr(s.having, shadow),
            order_by=[A.OrderItem(rew_expr(o.expr, shadow), o.ascending,
                                  o.nulls_first) for o in s.order_by])

    if isinstance(stmt, (A.Select, A.SetOp, A.WithSelect)):
        new_stmt = rew_stmt(stmt, EMPTY)
        return (new_stmt, True) if changed[0] else (stmt, False)
    if isinstance(stmt, (A.Update, A.Delete)):
        cmd = "update" if isinstance(stmt, A.Update) else "delete"
        f = _policy_predicate(cl, role, stmt.table, cmd)
        # embedded subqueries (WHERE / SET) read through RLS too,
        # regardless of whether the TARGET table has policies
        new_where = rew_expr(stmt.where, EMPTY)
        if isinstance(stmt, A.Update):
            new_assign = [(c, rew_expr(e, EMPTY))
                          for c, e in stmt.assignments]
        if f is None:
            if isinstance(stmt, A.Update):
                return (dataclasses.replace(
                    stmt, assignments=new_assign, where=new_where),
                    changed[0])
            return dataclasses.replace(stmt, where=new_where), changed[0]
        if isinstance(stmt, A.Update):
            _rls_check_update(cl, role, stmt)
        where = f if new_where is None else A.BinOp("and", new_where, f)
        if isinstance(stmt, A.Update):
            return (dataclasses.replace(
                stmt, assignments=new_assign, where=where), True)
        return dataclasses.replace(stmt, where=where), True
    if isinstance(stmt, A.Insert):
        # the SELECT source / row expressions read through RLS
        new_select = (rew_stmt(stmt.select, EMPTY)
                      if stmt.select is not None else None)
        new_rows = ([[rew_expr(v, EMPTY) for v in row]
                     for row in stmt.rows] if stmt.rows else stmt.rows)
        f = _policy_predicate(cl, role, stmt.table, "insert",
                                   kind="check")
        if f is None:
            if changed[0]:
                return dataclasses.replace(
                    stmt, select=new_select, rows=new_rows), True
            return stmt, False
        if stmt.select is not None or not stmt.rows:
            raise UnsupportedFeatureError(
                "INSERT ... SELECT under row-level security is not "
                "supported")
        t = cl.catalog.table(stmt.table)
        cols = stmt.columns or t.schema.names
        for row in stmt.rows:
            subst = {c: v for c, v in zip(cols, row)}
            checked = _subst_args(f, subst)
            try:
                ok = _eval_const(checked)
            except Exception:
                raise UnsupportedFeatureError(
                    "row-level security WITH CHECK over non-constant "
                    "inserts is not supported")
            if ok is not True:
                raise AnalysisError(
                    f'new row violates row-level security policy for '
                    f'table "{stmt.table}"')
        return (dataclasses.replace(stmt, rows=new_rows), True) \
            if changed[0] else (stmt, False)
    return stmt, False

def _rls_check_update(cl, role: str, stmt: A.Update) -> None:
    """WITH CHECK enforcement for UPDATE: the NEW row must satisfy
    the policy (PostgreSQL raises when an update rewrites a row out
    of policy scope).  Assigned-constant columns substitute into the
    check expression; a fully-constant result enforces directly;
    assignments that don't touch any check column are safe when the
    check falls back to USING (the untouched columns already passed
    it); anything else fails closed."""
    eff = _policy_predicate(cl, role, stmt.table, "update",
                                 kind="check")
    if eff is None:
        return
    from citus_tpu.planner.recursive import (
        _walk_columns as _walk_ast_columns,
    )
    check_cols = {c.name for c in _walk_ast_columns(eff)
                  if c.table is None}
    assigned = dict(stmt.assignments)
    subst = {}
    for col, val in assigned.items():
        if col in check_cols:
            subst[col] = val
    if subst:
        checked = _subst_args(eff, subst)
        remaining = {c.name for c in _walk_ast_columns(checked)}
        if remaining:
            raise UnsupportedFeatureError(
                "cannot verify row-level security WITH CHECK for this "
                "UPDATE (non-constant or mixed-column assignment)")
        try:
            ok = _eval_const(checked)
        except Exception:
            raise UnsupportedFeatureError(
                "cannot verify row-level security WITH CHECK for this "
                "UPDATE (non-constant assignment)")
        if ok is not True:
            raise AnalysisError(
                "new row violates row-level security policy for "
                f'table "{stmt.table}"')
        return
    # no check column assigned: safe only when check == using (the
    # unchanged columns already satisfied USING via the row filter)
    using = _policy_predicate(cl, role, stmt.table, "update",
                                   kind="using")
    if repr(eff) != repr(using):
        raise UnsupportedFeatureError(
            "cannot verify row-level security WITH CHECK for this "
            "UPDATE (policy has a distinct WITH CHECK expression)")

def _fire_triggers(cl, stmt: A.Statement, depth: int = 0) -> None:
    """Statement-level AFTER triggers: run each matching trigger's
    function body after a DML statement completes (reference:
    commands/trigger.c; bodies are stored SQL statements)."""
    if isinstance(stmt, A.Insert):
        table, event = stmt.table, "insert"
    elif isinstance(stmt, A.Update):
        table, event = stmt.table, "update"
    elif isinstance(stmt, A.Delete):
        table, event = stmt.table, "delete"
    elif isinstance(stmt, A.Merge):
        # MERGE may insert, update, or delete: fire all three
        for evt in ("insert", "update", "delete"):
            _fire_triggers_for(cl, stmt.target.name, evt, depth)
        return
    else:
        return
    _fire_triggers_for(cl, table, event, depth)

def _fire_triggers_for(cl, table: str, event: str, depth: int) -> None:
    matching = [t for t in cl.catalog.triggers.values()
                if t["table"] == table and t["event"] == event]
    if not matching:
        return
    if depth >= 8:
        raise ExecutionError(
            "trigger recursion limit exceeded (8 levels)")
    for trig in matching:
        fn = cl.catalog.functions.get(trig["function"])
        if fn is None:
            continue
        for body_stmt in parse_sql(fn["body"]):
            cl._execute_stmt(body_stmt)
            _fire_triggers(cl, body_stmt, depth + 1)

def _check_privileges(cl, role: str, stmt: A.Statement) -> None:
    """Table-level privilege enforcement for a non-superuser role
    (reference: standard ACLs propagated by commands/grant.c; a
    missing grant denies).  DDL and utility statements require
    superuser (role=None)."""
    from citus_tpu.errors import CatalogError
    if role not in cl.catalog.roles:
        raise CatalogError(f'role "{role}" does not exist')

    def deny(priv, table):
        raise CatalogError(
            f'permission denied for {table}: role "{role}" lacks {priv}')

    def tables_of(item):
        if isinstance(item, A.TableRef):
            return [item.name]
        if isinstance(item, A.SubqueryRef):
            return stmt_tables(item.select)
        if isinstance(item, A.Join):
            return tables_of(item.left) + tables_of(item.right)
        return []

    def expr_subselects(e):
        from citus_tpu.planner.recursive import _walk_expr
        if e is None or not isinstance(e, A.Expr):
            return []
        return [n.select for n in _walk_expr(e)]

    def stmt_tables(s):
        if isinstance(s, A.SetOp):
            return stmt_tables(s.left) + stmt_tables(s.right)
        if not isinstance(s, A.Select):
            return []
        out = tables_of(s.from_) if s.from_ is not None else []
        # subqueries anywhere in expressions read tables too
        exprs = ([i.expr for i in s.items] + [s.where, s.having]
                 + list(s.group_by) + [o.expr for o in s.order_by])
        for e in exprs:
            for sub in expr_subselects(e):
                out.extend(stmt_tables(sub))
        return out

    def check_read(s, skip=frozenset()):
        for t in stmt_tables(s):
            if t in skip:
                continue  # CTE name, not a real relation
            if not cl.catalog.has_privilege(role, t, "select"):
                deny("SELECT", t)

    if isinstance(stmt, (A.Select, A.SetOp)):
        check_read(stmt)
    elif isinstance(stmt, A.WithSelect):
        # a CTE's definition may reference only EARLIER CTE names —
        # a same-named reference inside its own body resolves to the
        # real relation and must be privilege-checked as one
        seen: set = set()
        for n, sel in stmt.ctes:
            check_read(sel, skip=frozenset(seen))
            seen.add(n)
        check_read(stmt.body, skip=frozenset(seen))
    elif isinstance(stmt, A.Insert):
        if not cl.catalog.has_privilege(role, stmt.table, "insert"):
            deny("INSERT", stmt.table)
        if stmt.on_conflict is not None \
                and stmt.on_conflict.action == "update" \
                and not cl.catalog.has_privilege(role, stmt.table,
                                                   "update"):
            # DO UPDATE modifies existing rows (PostgreSQL requires
            # UPDATE privilege in addition to INSERT)
            deny("UPDATE", stmt.table)
        if stmt.select is not None:
            check_read(stmt.select)
    elif isinstance(stmt, A.Update):
        if not cl.catalog.has_privilege(role, stmt.table, "update"):
            deny("UPDATE", stmt.table)
        for _c, e in stmt.assignments:
            for sub in expr_subselects(e):
                check_read(sub)
        for sub in expr_subselects(stmt.where):
            check_read(sub)
    elif isinstance(stmt, A.Delete):
        if not cl.catalog.has_privilege(role, stmt.table, "delete"):
            deny("DELETE", stmt.table)
        for sub in expr_subselects(stmt.where):
            check_read(sub)
    elif isinstance(stmt, A.Truncate):
        for name in (stmt.table,) + tuple(stmt.more):
            if not cl.catalog.has_privilege(role, name, "truncate"):
                deny("TRUNCATE", name)
    elif isinstance(stmt, (A.Prepare, A.ExecutePrepared, A.Deallocate)):
        # any role may manage prepared statements (PostgreSQL);
        # EXECUTE re-enters execute() with the same role, which
        # checks privileges on the underlying statement
        pass
    else:
        from citus_tpu.errors import CatalogError as _CE
        raise _CE(f'permission denied: role "{role}" cannot run '
                  f'{type(stmt).__name__} statements')
