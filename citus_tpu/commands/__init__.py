"""Per-statement command handlers.

Reference: src/backend/distributed/commands/ — the DistributeObjectOps
registry (distribute_object_ops.c:1-2307) maps every parse-tree node
type to its handler set; utility_hook.c dispatches through it.  Here the
same shape: ``registry`` keys AST statement types to handler functions,
``utility`` keys UDF-style admin calls by name.  ``cluster.Cluster``
owns the runtime (catalog, locks, sessions, executor wiring) and
delegates statement execution here.
"""

from citus_tpu.commands.registry import STATEMENT_HANDLERS, handles  # noqa: F401
