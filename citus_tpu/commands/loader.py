"""Import-time registration of all command handler modules.

Importing a handler module runs its ``@handles``/``@utility``
decorators, populating the registries.  Lazy (first dispatch) so the
handler modules may import citus_tpu.cluster helpers at module level
without a cycle.
"""

from __future__ import annotations

_loaded = False


def ensure_loaded() -> None:
    global _loaded
    if _loaded:
        return
    from citus_tpu.commands import ddl_objects, dml, tables, utility  # noqa: F401
    _loaded = True
