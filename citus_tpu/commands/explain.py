"""Distributed EXPLAIN [ANALYZE].

Reference: planner/multi_explain.c — distributed plan rendering with
"Tasks Shown: One of N" per-shard representative plans, EXPLAIN ANALYZE
piggybacking timings on execution, and strategy display for
INSERT..SELECT / set operations / grouping sets / joins.
"""

from __future__ import annotations

from citus_tpu.errors import UnsupportedFeatureError
from citus_tpu.executor import Result, execute_select
from citus_tpu.planner import ast as A
from citus_tpu.planner.bind import bind_select


def _execute_explain(cl, stmt: A.Explain) -> Result:
    if isinstance(stmt.statement, A.SetOp):
        so = stmt.statement
        lines = [f"Set Operation: {so.op.upper()}{' ALL' if so.all else ''}"]
        for side, sub in (("left", so.left), ("right", so.right)):
            r = _execute_explain(cl, A.Explain(sub, analyze=stmt.analyze))
            lines.append(f"  -> {side}:")
            lines.extend("     " + row[0] for row in r.rows)
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
    if isinstance(stmt.statement, (A.Update, A.Delete)):
        # modify-plan display (reference: EXPLAIN on the router /
        # multi-shard modify path shows the task distribution)
        m = stmt.statement
        t = cl.catalog.table(m.table)
        op = "Update" if isinstance(m, A.Update) else "Delete"
        if t.is_partitioned:
            from citus_tpu.partitioning import prune_partitions
            surv = prune_partitions(cl.catalog, t, m.where)
            lines = [f"{op} on {m.table} "
                     f"(partitions: {len(surv)}/"
                     f"{len(cl.catalog.partitions_of(m.table))})"]
            return Result(columns=["QUERY PLAN"],
                          rows=[(l,) for l in lines])
        from citus_tpu.planner.bind import Binder
        from citus_tpu.planner.physical import extract_intervals, prune_shards
        where = Binder(cl.catalog, t).bind_scalar(m.where) \
            if m.where is not None else None
        sis = prune_shards(t, where)
        lines = [f"{op} on {m.table} (shards: {len(sis)}/{len(t.shards)})"]
        ivs = [c.column for c in extract_intervals(where)] if where is not None else []
        if ivs:
            lines.append(f"  Shard/Chunk Pruning: {', '.join(sorted(set(ivs)))}")
        owners = {t.shards[si].placements[0] for si in sis}
        remote = {o for o in owners if cl.catalog.is_remote_node(o)}
        if remote and owners == remote and len(
                {cl.catalog.node_endpoint(o) for o in remote}) == 1:
            lines.append("  Strategy: forward to remote owner "
                         "(router, statement shipped as SQL)")
        elif remote:
            lines.append(f"  Strategy: cross-host two-phase commit "
                         f"({len(remote)} remote node(s))")
        else:
            lines.append("  Strategy: local (deletion bitmaps"
                         + (" + re-insert)" if op == "Update" else ")"))
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
    if isinstance(stmt.statement, A.Insert) \
            and stmt.statement.select is not None:
        ins = stmt.statement
        t = cl.catalog.table(ins.table)
        names = list(ins.columns or t.schema.names)
        strategy = "pull"
        sel = ins.select
        if isinstance(sel, A.Select) and isinstance(sel.from_, A.TableRef) \
                and not (sel.group_by or sel.having or sel.order_by
                         or sel.limit or sel.distinct):
            from citus_tpu.commands.insert import _insert_select_strategy
            try:
                bound = bind_select(cl.catalog, sel)
                if not bound.has_aggs and len(bound.final_exprs) == len(names):
                    strategy = _insert_select_strategy(
                        cl, t, bound, list(bound.final_exprs), names)
            # lint: disable=SWL01 -- EXPLAIN-only strategy probe; a bind failure falls back to the generic label
            except Exception:
                pass
        lines = [f"Insert into {ins.table} ({', '.join(names)})",
                 f"  Strategy: {strategy}"
                 + {"colocated": "  (per-shard pushdown, no re-hash)",
                    "repartition": "  (array-streaming re-hash)",
                    "pull": "  (coordinator row materialization)"}[strategy]]
        if isinstance(sel, (A.Select, A.SetOp)):
            sub = _execute_explain(cl, A.Explain(sel, analyze=False))
            lines.append("  -> source:")
            lines.extend("     " + row[0] for row in sub.rows)
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
    if not isinstance(stmt.statement, A.Select):
        raise UnsupportedFeatureError(
            "EXPLAIN supports SELECT, set operations, UPDATE/DELETE, "
            "and INSERT..SELECT")
    sel = stmt.statement
    if len(sel.group_by) == 1 and isinstance(sel.group_by[0],
                                             A.GroupingSetsSpec):
        spec = sel.group_by[0]
        full = max(spec.sets, key=len)
        lines = [f"Grouping Sets: {len(spec.sets)} grouped executions"]
        inner = A.Select(
            [i for i in sel.items
             if not (isinstance(i.expr, A.FuncCall)
                     and i.expr.name == "grouping")],
            sel.from_, sel.where, list(full))
        sub = _execute_explain(cl, A.Explain(inner, analyze=stmt.analyze))
        lines.extend("  " + row[0] for row in sub.rows)
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
    if isinstance(stmt.statement.from_, A.Join):
        return _explain_join(cl, stmt)
    sel0 = stmt.statement
    if isinstance(sel0.from_, A.TableRef) \
            and cl.catalog.has_table(sel0.from_.name) \
            and cl.catalog.table(sel0.from_.name).is_partitioned:
        from citus_tpu.partitioning import prune_partitions
        pt = cl.catalog.table(sel0.from_.name)
        parts = cl.catalog.partitions_of(pt.name)
        surv = prune_partitions(cl.catalog, pt, sel0.where)
        lines = [f"Append on {pt.name} "
                 f"(partitions: {len(surv)}/{len(parts)})"]
        if surv:
            import dataclasses as _dc
            rep = _dc.replace(sel0, from_=A.TableRef(
                surv[0].name, sel0.from_.alias or pt.name))
            sub = _execute_explain(cl, A.Explain(rep, analyze=False))
            lines.append(f"  Partitions Shown: One of {len(surv)}")
            lines.extend("  " + r[0] for r in sub.rows)
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
    if cl.catalog.rollups:
        from citus_tpu.rollup.routing import match_rollup
        m = match_rollup(cl, sel0)
        if m is not None:
            rname, rspec, rplan = m
            lines = [f"Rollup Scan on {rspec['table']} "
                     f"(rollup: {rname}, source: {rspec['source']})"]
            aggs = [f"{k}->{o}" for k, o, _p in rplan["items"]
                    if k != "group"]
            lines.append("  Finalize From Stored Sketches: "
                         + ", ".join(aggs))
            if rplan["groups"]:
                lines.append("  Re-merge GroupBy: "
                             + ", ".join(rplan["groups"]))
            if stmt.analyze:
                lines.extend(_run_analyze(cl, stmt))
            return Result(columns=["QUERY PLAN"],
                          rows=[(l,) for l in lines])
    bound = bind_select(cl.catalog, stmt.statement)
    from citus_tpu.planner.physical import plan_select
    plan = plan_select(cl.catalog, bound,
                       direct_limit=cl.settings.planner.direct_gid_limit)
    t = bound.table
    lines = []
    kind = ("Router" if plan.is_router else "Distributed") if t.is_distributed else "Local"
    lines.append(f"{kind} Scan on {t.name} "
                 f"(shards: {len(plan.shard_indexes)}/{t.shard_count})")
    if plan.index_eq is not None:
        icol, ival, iname = plan.index_eq
        if t.schema.scan_column(icol).type.is_text:
            # literal was bound to its dictionary id; show the string
            decoded = cl.catalog.decode_strings(t.name, icol, [int(ival)])
            ival = decoded[0] if decoded else ival
        lines.append(f"  Index Lookup: {icol} = {ival!r} using {iname}")
    if plan.intervals:
        lines.append("  Chunk Pruning: " +
                     ", ".join(sorted({c.column for c in plan.intervals})))
    if bound.has_aggs:
        mode = plan.group_mode
        desc = {"scalar": "Global Aggregate",
                "direct": f"Direct GroupBy (groups: {mode.n_groups}, combine: psum)",
                "hash_host": "Hash GroupBy (host combine)"}[mode.kind]
        lines.append(f"  Partial Aggregate per shard -> {desc}")
        lines.append(f"    Partials: " + ", ".join(
            f"{op.kind}[{op.dtype}]" for op in plan.partial_ops))
    if stmt.analyze:
        lines.extend(_run_analyze(cl, stmt))
    return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])


def _run_analyze(cl, stmt: A.Explain) -> list[str]:
    """Execute the statement under a FORCED trace and render every
    timing line from the resulting span tree (the same tree the
    Chrome-trace exporter and slow-query ring see), so EXPLAIN ANALYZE
    can never drift from the tracing instrumentation.

    Executes through the plan cache (keyed by the statement's AST repr,
    never the surrounding EXPLAIN text) so repeated ANALYZE shows real
    hit/miss + compile-amortization behavior."""
    from citus_tpu.executor.kernel_cache import plan_fingerprint
    from citus_tpu.observability import trace as _trace
    c0 = cl.counters.snapshot()
    qt = _trace.begin_query(f"explain analyze {stmt.statement!r:.80}",
                            cl.settings.observability, force=True)
    try:
        xbound, xplan, values, cache_hit = cl._cached_select_plan(
            stmt.statement, ("$explain", repr(stmt.statement)))
        r = execute_select(cl.catalog, xbound, cl.settings, plan=xplan,
                           param_values=values)
    finally:
        qt.finish()
    c1 = cl.counters.snapshot()
    tr = qt.trace
    _trace.set_last(tr)
    lines = []
    ex = tr.find("execute")
    elapsed_ms = ex.duration_ms if ex is not None \
        else r.explain["elapsed_s"] * 1000
    lines.append(f"  Rows: {r.rowcount}  Elapsed: {elapsed_ms:.2f} ms")
    ps = tr.find("plan")
    hit = ps.attrs.get("cache_hit", cache_hit) if ps is not None \
        else cache_hit
    fp = (ps.attrs.get("fingerprint") if ps is not None else None) \
        or plan_fingerprint(xplan)[:12]
    compile_ms = int(sum(s.duration_ms
                         for s in tr.find_all("kernel_compile")))
    lines.append(f"  Plan Cache: {'hit' if hit else 'miss'}  "
                 f"fingerprint {fp}  compile {compile_ms} ms")
    dh = c1.get("device_cache_hits", 0) - c0.get("device_cache_hits", 0)
    dm = c1.get("device_cache_misses", 0) - c0.get("device_cache_misses", 0)
    lines.append(f"  Device Cache: {dh} hit(s), {dm} miss(es)")
    # HBM odometer for THIS statement (hits replay resident bytes,
    # streams book the transfer) + what the cache holds resident now
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    hbm = (c1.get("device_hbm_touched_bytes", 0)
           - c0.get("device_hbm_touched_bytes", 0))
    mv = GLOBAL_CACHE.memory_view()
    lines.append(f"  Memory: {hbm} HBM bytes touched, "
                 f"cache-resident {mv['live_bytes']} bytes "
                 f"(high water {mv['high_water_bytes']})")
    mb = (ex.attrs.get("megabatch") if ex is not None else None) \
        or r.explain.get("megabatch")
    if mb:
        lines.append(
            f"  Batch: occupancy {mb.get('occupancy')}/"
            f"window {mb.get('window_ms', 0):g} ms  "
            f"(wait {mb.get('wait_ms', 0):.2f} ms)")
    rounds = tr.find_all("device_round")
    tasks = r.explain.get("tasks") or []
    if tasks:
        lines.append(f"  Tasks: {len(tasks)}  "
                     f"Tasks Shown: One of {len(tasks)}")
        si, nrows, dt = tasks[0]
        lines.append(f"    -> Task (shard index {si}): {nrows} rows, "
                     f"{dt*1000:.2f} ms device dispatch")
    elif rounds:
        lines.append(f"  Device Rounds: {len(rounds)}  "
                     f"({sum(s.duration_ms for s in rounds):.2f} ms)")
    rtasks = tr.find_all("remote_task")
    if rtasks:
        lines.append(f"  Remote Tasks: {len(rtasks)}")
        for s in rtasks:
            lines.append(
                f"    -> Task (shard index {s.attrs.get('shard_index')}): "
                f"pushed to node {s.attrs.get('node')}, "
                f"{s.attrs.get('bytes', 0)} result bytes, "
                f"{s.attrs.get('rpc_ms', 0):.2f} ms rpc, "
                f"{s.attrs.get('dec_ms', 0):.2f} ms decode")
    pl = (ex.attrs.get("pipeline") if ex is not None else None) \
        or r.explain.get("pipeline") or {}
    if pl:
        line = (
            f"  Pipeline: host decode {pl.get('host_decode_ms', 0):.2f}"
            f" ms, device {pl.get('device_ms', 0):.2f} ms, "
            f"H2D {pl.get('h2d_bytes', 0)} bytes, "
            f"stalls host={pl.get('host_stalls', 0)} "
            f"device={pl.get('device_stalls', 0)}")
        if "fused_dispatches" in pl:
            # the 1-dispatch-per-batch claim, visible per statement
            line += f", fused dispatches {pl['fused_dispatches']}"
        if "stream_window_peak_bytes" in pl:
            line += (f", stream window peak "
                     f"{pl['stream_window_peak_bytes']} bytes")
        lines.append(line)
        if "hash_slots" in pl:
            lines.append(
                f"    Hash: hash slots {pl['hash_slots']}, "
                f"occupancy {pl.get('hash_occupancy_pct', 0):g}%, "
                f"spilled {pl.get('hash_spilled_rows', 0)} rows")
        if "remote_wait_ms" in pl:
            wire = f", wire {pl['wire_format']}" \
                if pl.get("wire_format") else ""
            lines.append(
                f"    Remote Wait: {pl['remote_wait_ms']:.2f} ms "
                f"(overlapped {pl['remote_overlapped_ms']:.2f} ms, "
                f"peak in-flight {pl['remote_inflight_peak']}{wire})")
    return lines

def _explain_join(cl, stmt: A.Explain) -> Result:
    from citus_tpu.executor.join_executor import execute_join_select
    from citus_tpu.planner.join_planner import bind_join_select
    bj = bind_join_select(cl.catalog, stmt.statement)
    lines = [f"Join ({bj.strategy}) over {len(bj.rels)} relations"]
    for s_ in bj.steps:
        keys = ", ".join(f"{l} = {r}" for l, r in
                         zip(s_.left_keys, s_.right_keys)) or "(cross)"
        lines.append(f"  {s_.kind.upper()} JOIN {s_.right_alias} ON {keys}")
    for alias, _t in bj.rels:
        rp = bj.rel_plans[alias]
        f = f" filter: {rp.filter}" if rp.filter is not None else ""
        lines.append(f"  Scan {alias} [{', '.join(rp.columns)}]{f}")
    if bj.has_aggs:
        lines.append(f"  GroupBy keys={len(bj.group_keys)} "
                     f"partials={len(bj.partial_ops)} (host combine)")
    if stmt.analyze:
        r = execute_join_select(cl.catalog, bj, cl.settings)
        lines.append(f"  Rows: {r.rowcount}  Tasks: {r.explain['tasks']}  "
                     f"Elapsed: {r.explain['elapsed_s']*1000:.2f} ms")
    return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
