"""INSERT / upsert / INSERT..SELECT handlers.

Reference: multi-row INSERT routing (multi_router_planner.c
BuildRoutesForInsert), ON CONFLICT within one shard group, and the
3-strategy INSERT..SELECT ladder (insert_select_planner.c:
colocated-pushdown / repartition / pull-to-coordinator) — here the
direct strategies move arrays shard-to-shard without materializing
rows through the coordinator.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from citus_tpu.errors import (
    AnalysisError, CatalogError, ExecutionError, UnsupportedFeatureError,
)
from citus_tpu.executor import Result
from citus_tpu.ingest import TableIngestor, rows_to_columns
from citus_tpu.planner import ast as A
from citus_tpu.planner.bind import bind_select

from citus_tpu.cluster import (  # noqa: E402  (loaded post-cluster)
    _eval_const, _expand_returning_items, _pylit, _subst_excluded,
)


def execute_insert(cl, stmt: A.Insert) -> Result:
    t = cl.catalog.table(stmt.table)
    if stmt.select is not None:
        if stmt.returning:
            raise UnsupportedFeatureError(
                "RETURNING on INSERT..SELECT is not supported")
        if stmt.on_conflict is not None:
            # pull the source rows, then run the same upsert machinery
            # row literals take (reference: INSERT..SELECT ON CONFLICT
            # goes through the pull / colocated-intermediate-results
            # strategy, insert_select_executor.c README:1223-1238)
            inner = cl._execute_stmt(stmt.select)
            rows = [list(r) for r in inner.rows]
            r = _execute_upsert(cl, t, stmt, rows)
            r.explain["strategy"] = "insert_select:upsert_pull"
            return r
        names = stmt.columns or t.schema.names
        # FK-constrained, unique-indexed, and partitioned targets —
        # and partitioned sources — take the pull path: copy_from's
        # probes and partition routing only run there, and a
        # partitioned source must expand through _execute_stmt
        def _refs_partitioned(item) -> bool:
            if isinstance(item, A.Join):
                return _refs_partitioned(item.left) \
                    or _refs_partitioned(item.right)
            return (isinstance(item, A.TableRef)
                    and cl.catalog.has_table(item.name)
                    and cl.catalog.table(item.name).is_partitioned)
        direct_ok = not (t.foreign_keys or t.unique_indexes
                         or t.is_partitioned
                         or cl._domain_columns_of(t))
        if direct_ok and isinstance(stmt.select, A.Select) \
                and stmt.select.from_ is not None:
            direct_ok = not _refs_partitioned(stmt.select.from_)
        res = None if not direct_ok \
            else _insert_select_arrays(cl, t, stmt.select, list(names))
        if res is None:
            # general path: materialize rows through the coordinator
            # (reference: the pull-to-coordinator INSERT..SELECT
            # strategy, insert_select_executor.c)
            inner = cl._execute_stmt(stmt.select)
            n = cl.copy_from(stmt.table, rows=inner.rows,
                               column_names=list(names))
            strategy = "pull"
        else:
            n, strategy = res
        return Result(columns=[], rows=[],
                      explain={"inserted": n,
                               "strategy": f"insert_select:{strategy}"})
    rows = []
    for row_exprs in stmt.rows:
        row = []
        for e in row_exprs:
            if not isinstance(e, A.Literal):
                if isinstance(e, A.UnOp) and e.op == "-" and isinstance(e.operand, A.Literal):
                    row.append(-e.operand.value)
                    continue
                if isinstance(e, A.FuncCall) and e.name in ("nextval", "currval") \
                        and e.args and isinstance(e.args[0], A.Literal):
                    seq = str(e.args[0].value)
                    row.append(cl.catalog.nextval(seq) if e.name == "nextval"
                               else cl.catalog.currval(seq))
                    continue
                raise UnsupportedFeatureError("INSERT VALUES must be literals")
            row.append(e.value)
        rows.append(row)
    # resolve DEFAULTs up front (serial ids included) so ON CONFLICT
    # and RETURNING see exactly what gets stored — copy_from then
    # receives the complete batch and never draws defaults again
    names = list(t.schema.names if stmt.columns is None else stmt.columns)
    has_defaults = any(c.default_sql and c.name not in names
                       for c in t.schema)
    if has_defaults and rows:
        from citus_tpu.ingest import rows_to_columns
        listed = set(names)
        columns = {c: v for c, v in
                   rows_to_columns(t.schema.names, rows, names).items()
                   if c in listed
                   or not t.schema.column(c).default_sql}
        columns = cl._fill_defaults(t, columns)
        names = [c for c in t.schema.names if c in columns]
        rows = [tuple(columns[c][i] for c in names)
                for i in range(len(rows))]
        stmt = __import__("dataclasses").replace(stmt, columns=names)
    if stmt.on_conflict is not None:
        return _execute_upsert(cl, t, stmt, rows)
    n = cl.copy_from(stmt.table, rows=rows, column_names=names)
    if stmt.returning:
        out_rows = []
        for row in rows:
            m = {}
            for cn, v in zip(names, row):
                typ = t.schema.column(cn).type
                if v is not None and not typ.is_text:
                    # what a subsequent SELECT would read back
                    v = typ.from_physical(typ.to_physical(v))
                lit = A.Literal(v, "null" if v is None else
                                "string" if isinstance(v, str) else "int")
                m[A.ColumnRef(cn)] = lit
                m[A.ColumnRef(cn, stmt.table)] = lit
            for cn in t.schema.names:
                m.setdefault(A.ColumnRef(cn), A.Literal(None, "null"))
                m.setdefault(A.ColumnRef(cn, stmt.table),
                             A.Literal(None, "null"))
            exp = _expand_returning_items(t, stmt.returning, m)
            out_rows.append(tuple(_eval_const(e) for e, _ in exp))
        cols = [a for _, a in _expand_returning_items(t, stmt.returning)]
        return Result(columns=cols, rows=out_rows,
                      explain={"inserted": n})
    return Result(columns=[], rows=[], explain={"inserted": n})

def _execute_upsert(cl, t, stmt: A.Insert, rows: list) -> Result:
    """INSERT ... ON CONFLICT: the conflict target is the declared
    key (the reference requires it to include the distribution
    column so conflicts resolve within one shard group —
    multi_router_planner.c rejects others).  Runs under the
    colocation group's EXCLUSIVE write lock so check+write is atomic
    against concurrent writers and shard moves."""
    oc = stmt.on_conflict
    if stmt.returning:
        raise UnsupportedFeatureError(
            "RETURNING with ON CONFLICT is not supported")
    if not oc.targets:
        raise UnsupportedFeatureError(
            "ON CONFLICT requires an explicit (column, ...) target")
    names = list(t.schema.names if stmt.columns is None else stmt.columns)
    for c in oc.targets:
        if not t.schema.has(c):
            raise AnalysisError(f"column {c!r} does not exist")
        if c not in names:
            raise AnalysisError(
                "ON CONFLICT target columns must be inserted columns")
    if t.is_distributed and t.dist_column not in oc.targets:
        raise UnsupportedFeatureError(
            "ON CONFLICT target must include the distribution column")
    for c, _e in oc.assignments:
        if not t.schema.has(c):
            raise AnalysisError(f"column {c!r} does not exist")
        if t.is_distributed and c == t.dist_column:
            raise UnsupportedFeatureError(
                "ON CONFLICT DO UPDATE cannot modify the distribution "
                "column")
    # sketch_merge(col, excluded.col) assignments merge serialized
    # sketch states host-side before the UPDATE runs: the batched probe
    # below fetches the stored word alongside the conflict key, the
    # rollup codec merges it with the proposed row's word, and the
    # assignment collapses to a plain string literal (which the UPDATE
    # path dictionary-encodes like any other text-routed value)
    merge_cols: list = []
    for c, e in oc.assignments:
        if isinstance(e, A.FuncCall) and e.name == "sketch_merge":
            if t.schema.column(c).type.kind != "sketch":
                raise AnalysisError(
                    f"sketch_merge() target column {c!r} is not a "
                    f"sketch column")
            if len(e.args) != 2 \
                    or not (isinstance(e.args[0], A.ColumnRef)
                            and e.args[0].name == c
                            and e.args[0].table in (None, t.name)) \
                    or not (isinstance(e.args[1], A.ColumnRef)
                            and e.args[1].table == "excluded"
                            and e.args[1].name == c):
                raise AnalysisError(
                    "sketch_merge() must be written as "
                    "sketch_merge(col, excluded.col) on the assigned "
                    "column")
            merge_cols.append(c)
    key_idx = [names.index(c) for c in oc.targets]

    def norm_key(vals) -> tuple:
        """Canonicalize proposed key values to what a SELECT reads
        back (physical round-trip), so they compare equal to probed
        rows: 5.0 -> Decimal('5.00'), '2020-01-01' -> date."""
        out = []
        for c, v in zip(oc.targets, vals):
            typ = t.schema.column(c).type
            if v is None:
                out.append(v)
            elif typ.is_text:
                if typ.kind != "text":
                    # uuid/bytea/array: a non-canonical spelling must
                    # collide with the stored canonical word, then read
                    # back the way a SELECT renders it
                    out.append(typ.render_word(typ.normalize_word(v)))
                else:
                    out.append(v)
            else:
                out.append(typ.from_physical(typ.to_physical(v)))
        return tuple(out)

    if oc.action == "update":
        # PostgreSQL raises error 21000 whenever two proposed rows
        # would affect the same target row; checking up front keeps
        # the statement all-or-nothing (no partially applied updates)
        dup_check: set = set()
        for row in rows:
            raw = tuple(row[i] for i in key_idx)
            if any(v is None for v in raw):
                continue
            key = norm_key(raw)
            if key in dup_check:
                raise ExecutionError(
                    "ON CONFLICT DO UPDATE command cannot affect row "
                    "a second time")
            dup_check.add(key)
    inserted = updated = skipped = 0
    from citus_tpu.transaction.locks import EXCLUSIVE
    with cl._write_lock(t, EXCLUSIVE):
        # one batched probe instead of a per-row count(*) under the
        # lock: fetch the conflict-target columns of candidate rows
        # (pruned by the distribution-column IN-list) into a set
        probe_rows = [row for row in rows
                      if not any(row[i] is None for i in key_idx)]
        # conflict key -> stored values of the sketch-merge source
        # columns (an empty tuple when none are requested)
        existing: dict = {}
        if probe_rows:
            where = None
            if t.is_distributed and t.dist_column in names:
                di = names.index(t.dist_column)
                dvals = sorted({row[di] for row in probe_rows})
                where = A.InList(A.ColumnRef(t.dist_column),
                                 tuple(_pylit(v) for v in dvals), False)
            chk = A.Select([A.SelectItem(A.ColumnRef(c))
                            for c in list(oc.targets) + merge_cols],
                           A.TableRef(t.name), where)
            nk = len(oc.targets)
            existing = {tuple(r[:nk]): tuple(r[nk:])
                        for r in cl._execute_stmt(chk).rows}
        to_insert: list = []
        affected: set = set()  # keys inserted/updated by this command
        for row in rows:
            raw = tuple(row[i] for i in key_idx)
            if any(v is None for v in raw):
                # NULL never equals NULL: no conflict possible
                to_insert.append(row)
                inserted += 1
                continue
            key = norm_key(raw)
            if key in affected:
                # only reachable for DO NOTHING (DO UPDATE duplicate
                # keys were rejected before any mutation)
                skipped += 1
                continue
            if key not in existing:
                affected.add(key)
                to_insert.append(row)
                inserted += 1
                continue
            if oc.action == "nothing":
                skipped += 1
                continue
            affected.add(key)
            cond = None
            for c, v in zip(oc.targets, raw):
                eq = A.BinOp("=", A.ColumnRef(c), _pylit(v))
                cond = eq if cond is None else A.BinOp("and", cond, eq)
            excl = {c: _pylit(v) for c, v in zip(names, row)}
            stored = dict(zip(merge_cols, existing.get(key, ())))
            assignments = []
            for c, e in oc.assignments:
                e2 = _subst_excluded(e, excl)
                if c in stored and isinstance(e2, A.FuncCall) \
                        and e2.name == "sketch_merge":
                    from citus_tpu.rollup.sketches import (
                        merge_sketch_words,
                    )
                    cur = stored[c]
                    new = e2.args[1].value \
                        if isinstance(e2.args[1], A.Literal) else None
                    if cur is None or new is None:
                        merged = new if cur is None else cur
                    else:
                        merged = merge_sketch_words(str(cur), str(new))
                    e2 = A.Literal(merged,
                                   "null" if merged is None else "string")
                assignments.append((c, e2))
            where = cond
            if oc.where is not None:
                where = A.BinOp("and", cond,
                                _subst_excluded(oc.where, excl))
            upd: A.Statement = A.Update(t.name, assignments, where)
            import threading as _threading
            exec_role = cl._exec_roles.get(_threading.get_ident())
            if exec_role is not None:
                # the conflicting row must pass the role's UPDATE
                # policies regardless of the conflict WHERE clause
                # (PostgreSQL raises the RLS violation whenever the
                # existing row fails USING)
                pol = cl._policy_predicate(exec_role, t.name,
                                             "update")
                if pol is not None:
                    vis = A.Select(
                        [A.SelectItem(A.FuncCall("count", (A.Star(),)))],
                        A.TableRef(t.name), A.BinOp("and", cond, pol))
                    if not cl._execute_stmt(vis).rows[0][0]:
                        raise AnalysisError(
                            f'new row violates row-level security '
                            f'policy for table "{t.name}"')
                upd, _ = cl._apply_rls(exec_role, upd)
            r = cl._execute_stmt(upd)
            n_upd = r.explain.get("updated", 0)
            updated += n_upd
            skipped += 0 if n_upd else 1  # DO UPDATE ... WHERE filtered
        if to_insert:
            cl.copy_from(t.name, rows=to_insert,
                           column_names=stmt.columns)
    if oc.action == "update":
        # PostgreSQL fires statement-level UPDATE triggers whenever
        # DO UPDATE is specified (INSERT triggers fire at execute())
        cl._fire_triggers_for(t.name, "update", 0)
    return Result(columns=[], rows=[],
                  explain={"inserted": inserted, "updated": updated,
                           "skipped": skipped, "strategy": "upsert"})

def _insert_select_arrays(cl, target, sel: A.Select,
                          names: list[str]) -> Optional[int]:
    """Array-streaming INSERT..SELECT (the repartition strategy,
    reference: insert_select_planner.c IsRedistributablePlan): when
    the SELECT is a plain single-table projection whose output types
    match the target physically, move numpy columns straight from
    the scan into the hash-routing ingest — no Python row
    materialization.  Returns None when ineligible."""
    if not isinstance(sel, A.Select) or not isinstance(sel.from_, A.TableRef):
        return None
    if sel.group_by or sel.having or sel.order_by or sel.limit or sel.distinct:
        return None
    if cl.catalog.remote_data is not None and any(
            cl.catalog.is_remote_node(nd)
            for s in target.shards for nd in s.placements):
        # remote-hosted target shards: only the pull path routes rows
        # over the data plane (copy_from's _route_remote_batch); the
        # array strategies write placements directly and would drop or
        # misplace rows for foreign hosts
        return None
    try:
        bound = bind_select(cl.catalog, sel)
    except Exception:
        return None
    if bound.has_aggs or len(bound.final_exprs) != len(names):
        return None
    from citus_tpu.planner.bound import (
        BColumn, BDictRemap, compile_expr, predicate_mask,
    )
    from citus_tpu.planner.physical import plan_select
    final_exprs = list(bound.final_exprs)
    for i, (e, cname) in enumerate(zip(final_exprs, names)):
        tgt = target.schema.column(cname).type
        if e.type != tgt:
            return None
        if tgt.kind == "uuid":
            # uuid lanes travel in pairs; the pull path rematerializes
            # canonical strings and re-encodes both lanes on ingest
            return None
        if tgt.is_text:
            if not isinstance(e, BColumn):
                return None
            if bound.table.name != target.name or e.name != cname:
                # re-encode source dictionary ids into the target's
                # dictionary space (grows the target dictionary)
                src_words = cl.catalog.dictionary(bound.table.name, e.name)
                mapping = tuple(int(x) for x in cl.catalog.encode_strings(
                    target.name, cname, src_words))
                final_exprs[i] = BDictRemap(e, mapping)
    plan = plan_select(cl.catalog, bound,
                       direct_limit=cl.settings.planner.direct_gid_limit)
    from citus_tpu.transaction.locks import SHARED
    fns = [compile_expr(e, np) for e in final_exprs]
    ffn = compile_expr(bound.filter, np) if bound.filter is not None else None
    strategy = _insert_select_strategy(cl, target, bound, final_exprs, names)
    with cl._write_lock(target, SHARED):
        n = _run_insert_select_arrays(cl, 
            target, bound, plan, fns, ffn, names, strategy)
    return n, strategy

def _insert_select_strategy(cl, target, bound, final_exprs, names) -> str:
    """The reference's INSERT..SELECT strategy ladder
    (insert_select_planner.c, README:1187-1238): *colocated pushdown*
    when source and target share a colocation group and the target's
    distribution column is fed directly by the source's distribution
    column (rows already live on the right shard — no re-hash, no
    routing); else *repartition* (array-streaming re-hash through the
    hash-routing ingest).  The caller falls back to *pull* (row
    materialization) when the arrays path is ineligible entirely."""
    from citus_tpu.planner.bound import BColumn
    src = bound.table
    if not (src.is_distributed and target.is_distributed):
        return "repartition"
    if src.colocation_id != target.colocation_id:
        return "repartition"
    if target.dist_column is None or target.dist_column not in names:
        return "repartition"
    i = names.index(target.dist_column)
    e = final_exprs[i]
    # plain column (no dict remap / cast) referencing the source's
    # distribution column: hash(source row) == hash(target row)
    if isinstance(e, BColumn) and e.name == src.dist_column:
        return "colocated"
    return "repartition"

def _run_insert_select_arrays(cl, target, bound, plan, fns, ffn,
                              names, strategy) -> int:
    from citus_tpu.storage.overlay import current_overlay
    txn = current_overlay()
    if txn is not None:
        # inside BEGIN..COMMIT: stage under the transaction's xid.
        # On failure, register staged dirs (never abort the xid —
        # that would destroy earlier statements' staged rows)
        ing = TableIngestor(cl.catalog, target, txlog=None)
        ing.xid = txn.xid
        try:
            total = _stream_insert_select(cl, ing, target, bound, plan,
                                               fns, ffn, names, strategy)
            for w in ing._writers.values():
                w.flush()
        finally:
            txn.record_ingest(
                target.name,
                [w.directory for w in ing._writers.values()])
        cl.counters.bump("rows_ingested", total)
        return total
    ing = TableIngestor(cl.catalog, target, txlog=cl.txlog)
    try:
        total = _stream_insert_select(cl, ing, target, bound, plan,
                                           fns, ffn, names, strategy)
    except BaseException:
        ing.abort()  # failure during scan/append: staged files dropped
        raise
    # finish() manages its own failure path (releases the xid so
    # recovery decides; aborting here could roll back a logged COMMIT)
    ing.finish()
    cl.counters.bump("rows_ingested", total)
    return total

def _stream_insert_select(cl, ing, target, bound, plan, fns, ffn,
                          names, strategy) -> int:
    from citus_tpu.executor.batches import load_shard_batches
    from citus_tpu.planner.bound import predicate_mask
    total = 0
    for si in plan.shard_indexes:
        for values, masks, n in load_shard_batches(
                cl.catalog, plan, si, min_batch_rows=1):
            env = {c: (values[c].astype(
                        bound.table.schema.scan_dtype(c, device=True), copy=False),
                       masks[c]) for c in plan.scan_columns}
            if ffn is not None:
                m = np.asarray(predicate_mask(np, ffn, env, np.ones(n, bool)))
                if m.shape == ():
                    m = np.full(n, bool(m))
            else:
                m = np.ones(n, bool)
            idx = np.nonzero(m)[0]
            if idx.size == 0:
                continue
            out_v, out_m = {}, {}
            for fn, cname in zip(fns, names):
                v, valid = fn(env)
                v = np.asarray(v)
                if v.ndim == 0:
                    v = np.broadcast_to(v, (n,))
                if valid is True:
                    valid = np.ones(n, bool)
                elif valid is False:
                    valid = np.zeros(n, bool)
                st = target.schema.column(cname).type.storage_dtype
                out_v[cname] = v[idx].astype(st)
                out_m[cname] = np.asarray(valid)[idx]
            for cname in target.schema.names:
                if cname not in out_v:
                    out_v[cname] = np.zeros(idx.size, target.schema.column(cname).type.storage_dtype)
                    out_m[cname] = np.zeros(idx.size, bool)
            if strategy == "colocated":
                # pushdown: rows of source shard si belong to target
                # shard si by construction — write straight to its
                # placements, skipping hash + scatter entirely
                shard = target.shards[si]
                for node in shard.placements:
                    ing._writer(shard.shard_id, node).append_batch(out_v, out_m)
            else:
                ing.append(out_v, out_m)
            total += idx.size
    return total
