"""Column type system.

Design goal: every SQL type maps to a fixed-width *physical* representation
that a TPU kernel can process, with exact (bit-identical) aggregate
semantics for the types the reference's analytics path cares about
(reference: the NUMERIC/aggregate machinery used by
multi_logical_optimizer.c's worker/master aggregate split).

Physical encodings:

=============  =====================  ============================
SQL type       storage dtype          semantics
=============  =====================  ============================
BOOL           int8                   0/1
SMALLINT       int16                  widened to int64 on device
INT/INTEGER    int32                  widened to int64 on device
BIGINT         int64
REAL           float32
DOUBLE         float64
DECIMAL(p,s)   int64                  value * 10**s (exact fixed point)
DATE           int32                  days since 1970-01-01
TIMESTAMP      int64                  microseconds since epoch
TEXT/VARCHAR   int32                  table-global dictionary id
=============  =====================  ============================

Exactness: DECIMAL arithmetic and SUM/AVG run on scaled int64, so results
are bit-identical regardless of reduction order — this is what lets the
per-shard partial aggregate + ``psum`` combine reproduce the single-node
answer exactly (the reference gets the same property from PostgreSQL's
arbitrary-precision NUMERIC).

Nulls are carried in a separate validity bitmap (storage) / bool mask
(device); the value slot under a null is 0.
"""

from __future__ import annotations

import datetime
import decimal
import re
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from citus_tpu.errors import AnalysisError

# type kinds
BOOL = "bool"
INT16 = "int16"
INT32 = "int32"
INT64 = "int64"
FLOAT32 = "float32"
FLOAT64 = "float64"
DECIMAL = "decimal"
DATE = "date"
TIMESTAMP = "timestamp"
TIMESTAMPTZ = "timestamptz"
TIME = "time"
INTERVAL = "interval"
TEXT = "text"
UUID = "uuid"
BYTEA = "bytea"
ARRAY = "array"
SKETCH = "sketch"

_EPOCH_DATE = datetime.date(1970, 1, 1)

_TZ_SUFFIX = re.compile(r"([+-]\d{2})(?::?(\d{2}))?$")


def _iso_compat(s: str) -> str:
    """Normalize ISO timestamp/time strings for fromisoformat on
    Python < 3.11, which rejects 'Z', bare '+HH'/'+HHMM' offsets
    (e.g. '2024-01-02 00:00:00+00' as PostgreSQL emits), and
    fractional seconds that are not exactly 3 or 6 digits."""
    if s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    # a trailing [+-]HH only means a zone offset after a time component;
    # without the colon guard a bare date's '-DD' would match
    off = ""
    m = _TZ_SUFFIX.search(s)
    if m and ":" in s[:m.start()]:
        off = m.group(1) + ":" + (m.group(2) or "00")
        s = s[:m.start()]
    fm = re.search(r"\.(\d{1,6})$", s)
    if fm and len(fm.group(1)) not in (3, 6):
        s = s[:fm.start(1)] + fm.group(1).ljust(6, "0")
    return s + off


def parse_datetime(s: str) -> datetime.datetime:
    return datetime.datetime.fromisoformat(_iso_compat(s))


def parse_time(s: str) -> datetime.time:
    return datetime.time.fromisoformat(_iso_compat(s))

_STORAGE_DTYPES = {
    BOOL: np.int8,
    INT16: np.int16,
    INT32: np.int32,
    INT64: np.int64,
    FLOAT32: np.float32,
    FLOAT64: np.float64,
    DECIMAL: np.int64,
    DATE: np.int32,
    TIMESTAMP: np.int64,
    TIMESTAMPTZ: np.int64,
    TIME: np.int64,
    INTERVAL: np.int64,
    TEXT: np.int32,
    UUID: np.int64,
    BYTEA: np.int32,
    ARRAY: np.int32,
    SKETCH: np.int32,
}

# dtype the expression/aggregate kernels compute in
_DEVICE_DTYPES = {
    BOOL: np.int32,
    INT16: np.int64,
    INT32: np.int64,
    INT64: np.int64,
    FLOAT32: np.float32,
    FLOAT64: np.float64,
    DECIMAL: np.int64,
    DATE: np.int32,
    TIMESTAMP: np.int64,
    TIMESTAMPTZ: np.int64,
    TIME: np.int64,
    INTERVAL: np.int64,
    TEXT: np.int32,
    UUID: np.int64,
    BYTEA: np.int32,
    ARRAY: np.int32,
    SKETCH: np.int32,
}


#: sketch word prefixes the SKETCH kind accepts ("<kind>:<version>:<b64>")
SKETCH_WORD_KINDS = ("hll", "ddsk", "topk", "tdg")

#: kinds whose physical value is a table-global dictionary id — the
#: fixed-width projection of variable-width data onto the TPU's shape
#: constraints (SURVEY "hard parts": dictionary/offset encodings at
#: write time so kernels see fixed-width ids).  The reference stores
#: arbitrary varlena datums in columnar chunks
#: (columnar/columnar_tableam.c:718); here every variable-width type
#: rides the dictionary machinery with kind-specific canonicalization
#: (normalize_word) and rendering (render_word).  UUID left this club:
#: it is already fixed-width (128 bits), so it stores as two int64
#: lanes per column and never touches the table-global dictionary.
_DICTIONARY_KINDS = (TEXT, BYTEA, ARRAY, SKETCH)


# ---- uuid lane encoding --------------------------------------------------
#
# A uuid column stores as TWO int64 streams: the base column holds the
# high 64 bits, a companion "<name>::lo" stream holds the low 64 bits.
# Both lanes are offset-binary (bit 63 flipped), so SIGNED int64 order
# on (hi, lo) equals unsigned 128-bit order equals canonical lowercase
# hex text order — chunk min/max stats on the lanes prune correctly and
# equality/ordering run directly on fixed-width lanes in the kernels.

#: companion-stream suffix ("::" cannot appear in a SQL identifier path
#: that reaches storage, so derived names never collide with user columns)
UUID_LANE_SUFFIX = "::lo"

_LANE_BIAS = 1 << 63
_U64 = (1 << 64) - 1


def is_uuid_lane(name: str) -> bool:
    return name.endswith(UUID_LANE_SUFFIX)


def uuid_lane_name(name: str) -> str:
    return name + UUID_LANE_SUFFIX


def uuid_lane_base(name: str) -> str:
    return name[:-len(UUID_LANE_SUFFIX)] if is_uuid_lane(name) else name


def uuid_int_to_lanes(value: int) -> tuple[int, int]:
    """128-bit uuid int -> (hi, lo) signed offset-binary int64 lanes."""
    return (((value >> 64) & _U64) - _LANE_BIAS), ((value & _U64) - _LANE_BIAS)


def uuid_lanes_to_int(hi: int, lo: int) -> int:
    """(hi, lo) signed offset-binary lanes -> 128-bit uuid int."""
    return (((int(hi) + _LANE_BIAS) & _U64) << 64) | ((int(lo) + _LANE_BIAS) & _U64)


def uuid_lane_arrays(values) -> tuple[np.ndarray, np.ndarray]:
    """Iterable of uuid spellings (str/UUID/None) -> (hi, lo) int64
    arrays (0 under nulls; validity is tracked separately)."""
    n = len(values)
    hi = np.zeros(n, np.int64)
    lo = np.zeros(n, np.int64)
    for i, v in enumerate(values):
        if v is None:
            continue
        h, l = uuid_int_to_lanes(UUID_T.to_physical(v))
        hi[i] = h
        lo[i] = l
    return hi, lo


def uuid_from_lane_pair(hi, lo) -> str:
    """One (hi, lo) lane pair -> canonical lowercase uuid string."""
    import uuid as _uuid
    return str(_uuid.UUID(int=uuid_lanes_to_int(hi, lo)))


@dataclass(frozen=True)
class ColumnType:
    kind: str
    precision: int = 0  # DECIMAL only
    scale: int = 0      # DECIMAL only
    elem: Optional[str] = None  # ARRAY only: element type name

    # ---- classification ------------------------------------------------
    @property
    def is_integer(self) -> bool:
        return self.kind in (INT16, INT32, INT64)

    @property
    def is_float(self) -> bool:
        return self.kind in (FLOAT32, FLOAT64)

    @property
    def is_decimal(self) -> bool:
        return self.kind == DECIMAL

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_float or self.is_decimal

    @property
    def is_text(self) -> bool:
        """Dictionary-encoded (text-routed) kinds: the physical value is
        a table-global dictionary id, and every code path that encodes/
        decodes through the dictionary treats these identically."""
        return self.kind in _DICTIONARY_KINDS

    @property
    def is_orderable_physical(self) -> bool:
        """True when physical-value order == logical order (everything but
        the dictionary kinds, whose ids are assigned in insertion order,
        and uuid, whose single-lane physical is only a partial order —
        the full order needs both lanes)."""
        return self.kind not in _DICTIONARY_KINDS and self.kind != UUID

    # ---- dictionary-kind canonicalization ------------------------------
    def normalize_word(self, value: Any) -> str:
        """Python value -> canonical dictionary word.  Different inputs
        that denote the same logical value must map to one word, or
        equality comparisons break (e.g. uppercase/lowercase uuids)."""
        k = self.kind
        if k == UUID:
            import uuid as _uuid
            try:
                return str(_uuid.UUID(str(value)))
            except (ValueError, AttributeError, TypeError):
                raise AnalysisError(
                    f"invalid input syntax for type uuid: {value!r}")
        if k == BYTEA:
            if isinstance(value, (bytes, bytearray, memoryview)):
                return "\\x" + bytes(value).hex()
            s = str(value)
            if s.startswith("\\x"):
                try:
                    bytes.fromhex(s[2:])
                except ValueError:
                    raise AnalysisError(
                        f"invalid hexadecimal data for bytea: {value!r}")
                return "\\x" + s[2:].lower()
            # PG escape-format / raw string: store its utf-8 bytes
            return "\\x" + s.encode().hex()
        if k == ARRAY:
            import json as _json
            if isinstance(value, str):
                try:
                    value = _json.loads(value)
                except ValueError:
                    raise AnalysisError(
                        f"invalid input syntax for type array: {value!r}")
            if isinstance(value, np.ndarray):
                value = value.tolist()
            if not isinstance(value, (list, tuple)):
                raise AnalysisError(
                    f"invalid input syntax for type array: {value!r}")
            et = _SQL_NAMES.get(self.elem or "")
            out = []
            for v in value:
                if v is None:
                    out.append(None)
                elif et is not None and et.is_numeric:
                    out.append(float(v) if et.is_float else int(v))
                else:
                    out.append(str(v) if not isinstance(
                        v, (int, float, bool)) else v)
            return _json.dumps(out, separators=(",", ":"))
        if k == SKETCH:
            # self-describing "<kind>:<version>:<base64 payload>" word;
            # the payload codec lives in rollup/sketches.py — the type
            # layer only guards the envelope so a stray string can't
            # enter a sketch column and break merges later
            s = str(value)
            parts = s.split(":", 2)
            if len(parts) != 3 or parts[0] not in SKETCH_WORD_KINDS \
                    or not parts[1].isdigit():
                raise AnalysisError(
                    f"invalid input syntax for type sketch: {value!r}")
            return s
        return str(value)

    def render_word(self, word: str) -> Any:
        """Canonical dictionary word -> Python value (result decode)."""
        k = self.kind
        if k == BYTEA:
            return bytes.fromhex(word[2:]) if word.startswith("\\x") \
                else word.encode()
        if k == ARRAY:
            import json as _json
            return _json.loads(word)
        return word

    # ---- dtypes --------------------------------------------------------
    @property
    def storage_dtype(self) -> np.dtype:
        return np.dtype(_STORAGE_DTYPES[self.kind])

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(_DEVICE_DTYPES[self.kind])

    # ---- value conversion ----------------------------------------------
    def to_physical(self, value: Any) -> int | float:
        """Python value -> physical scalar (dictionary ids handled by caller
        for TEXT)."""
        if value is None:
            return 0
        k = self.kind
        if k == BOOL:
            return 1 if value else 0
        if k in (INT16, INT32, INT64):
            return int(value)
        if k in (FLOAT32, FLOAT64):
            return float(value)
        if k == DECIMAL:
            d = value if isinstance(value, decimal.Decimal) else decimal.Decimal(str(value))
            q = d.scaleb(self.scale).to_integral_value(rounding=decimal.ROUND_HALF_UP)
            return int(q)
        if k == DATE:
            if isinstance(value, (int, np.integer)):
                # already-physical (days since epoch), matching the
                # numeric ndarray fast path in ingest.encode_columns
                return int(value)
            if isinstance(value, str):
                value = datetime.date.fromisoformat(value)
            return (value - _EPOCH_DATE).days
        if k == TIMESTAMP:
            if isinstance(value, str):
                value = parse_datetime(value)
            # integer arithmetic: float .timestamp() loses sub-us precision
            delta = value.replace(tzinfo=None) - datetime.datetime(1970, 1, 1)
            return delta // datetime.timedelta(microseconds=1)
        if k == TIMESTAMPTZ:
            if isinstance(value, str):
                value = parse_datetime(value)
            if value.tzinfo is None:
                # PostgreSQL interprets a naive input in the session
                # TimeZone; ours is pinned to UTC
                value = value.replace(tzinfo=datetime.timezone.utc)
            value = value.astimezone(datetime.timezone.utc)
            delta = value.replace(tzinfo=None) - datetime.datetime(1970, 1, 1)
            return delta // datetime.timedelta(microseconds=1)
        if k == TIME:
            if isinstance(value, str):
                value = parse_time(value)
            if isinstance(value, datetime.datetime):
                value = value.time()
            return (value.hour * 3_600_000_000
                    + value.minute * 60_000_000
                    + value.second * 1_000_000 + value.microsecond)
        if k == INTERVAL:
            if isinstance(value, datetime.timedelta):
                return value // datetime.timedelta(microseconds=1)
            return _parse_interval_us(str(value))
        if k == UUID:
            import uuid as _uuid
            if isinstance(value, _uuid.UUID):
                return value.int
            try:
                return _uuid.UUID(str(value)).int
            except (ValueError, AttributeError, TypeError):
                raise AnalysisError(
                    f"invalid input syntax for type uuid: {value!r}")
        raise AnalysisError(f"cannot convert value for type {self}")

    def from_physical(self, raw: int | float, null: bool = False) -> Any:
        """Physical scalar -> Python value (TEXT handled by caller)."""
        if null:
            return None
        k = self.kind
        if k == BOOL:
            return bool(raw)
        if k in (INT16, INT32, INT64):
            return int(raw)
        if k in (FLOAT32, FLOAT64):
            return float(raw)
        if k == DECIMAL:
            return decimal.Decimal(int(raw)).scaleb(-self.scale)
        if k == DATE:
            return _EPOCH_DATE + datetime.timedelta(days=int(raw))
        if k == TIMESTAMP:
            return datetime.datetime.fromtimestamp(raw / 1_000_000, tz=datetime.timezone.utc).replace(tzinfo=None)
        if k == TIMESTAMPTZ:
            # tz-aware, pinned UTC (our session TimeZone)
            return datetime.datetime.fromtimestamp(
                raw / 1_000_000, tz=datetime.timezone.utc)
        if k == TIME:
            us = int(raw)
            return datetime.time(us // 3_600_000_000,
                                 us // 60_000_000 % 60,
                                 us // 1_000_000 % 60, us % 1_000_000)
        if k == INTERVAL:
            return datetime.timedelta(microseconds=int(raw))
        if k == UUID:
            import uuid as _uuid
            return str(_uuid.UUID(int=int(raw)))
        raise AnalysisError(f"cannot convert value for type {self}")

    def __str__(self) -> str:
        if self.kind == DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        if self.kind == ARRAY:
            return f"{self.elem or 'text'}[]"
        return self.kind


#: microseconds per named interval unit (day-time intervals only: a
#: month has no fixed length in microseconds, so PG-style month/year
#: components are rejected rather than silently approximated)
_INTERVAL_UNITS_US = {
    "microsecond": 1, "microseconds": 1, "us": 1,
    "millisecond": 1_000, "milliseconds": 1_000, "ms": 1_000,
    "second": 1_000_000, "seconds": 1_000_000, "sec": 1_000_000,
    "secs": 1_000_000, "s": 1_000_000,
    "minute": 60_000_000, "minutes": 60_000_000, "min": 60_000_000,
    "mins": 60_000_000, "m": 60_000_000,
    "hour": 3_600_000_000, "hours": 3_600_000_000, "h": 3_600_000_000,
    "day": 86_400_000_000, "days": 86_400_000_000, "d": 86_400_000_000,
    "week": 7 * 86_400_000_000, "weeks": 7 * 86_400_000_000,
}


def _parse_interval_us(s: str) -> int:
    """'1 day 02:30:00', '3 hours', '-90 minutes', '00:00:01.5' ->
    microseconds.  Month/year components raise (no fixed us length)."""
    import re
    total = 0
    rest = s.strip().lower()
    if not rest:
        raise AnalysisError("invalid input syntax for type interval: ''")
    # leading sign applies to the whole literal (PG: '-1 day 02:00' is
    # compound; we keep the simpler whole-literal sign)
    sign = 1
    if rest.startswith("-") and not re.match(r"-\d+:\d", rest):
        sign, rest = -1, rest[1:].strip()
    # hh:mm:ss[.ffffff] tail
    m = re.search(r"(-?)(\d+):(\d{1,2})(?::(\d{1,2})(\.\d+)?)?\s*$", rest)
    if m:
        tsign = -1 if m.group(1) else 1
        us = (int(m.group(2)) * 3_600_000_000
              + int(m.group(3)) * 60_000_000
              + int(m.group(4) or 0) * 1_000_000)
        if m.group(5):
            us += round(float(m.group(5)) * 1_000_000)
        total += tsign * us
        rest = rest[:m.start()].strip()
    for num, unit in re.findall(r"(-?\d+(?:\.\d+)?)\s*([a-z]+)", rest):
        if unit in ("month", "months", "mon", "mons", "year", "years",
                    "y", "yr", "yrs"):
            raise AnalysisError(
                "interval month/year components are not supported "
                "(no fixed microsecond length); use days")
        mult = _INTERVAL_UNITS_US.get(unit)
        if mult is None:
            raise AnalysisError(
                f"invalid input syntax for type interval: {s!r}")
        total += round(float(num) * mult)
    consumed = re.sub(r"(-?\d+(?:\.\d+)?)\s*([a-z]+)", "", rest).strip()
    if consumed:
        raise AnalysisError(
            f"invalid input syntax for type interval: {s!r}")
    return sign * total


# canonical singletons
BOOL_T = ColumnType(BOOL)
INT16_T = ColumnType(INT16)
INT32_T = ColumnType(INT32)
INT64_T = ColumnType(INT64)
FLOAT32_T = ColumnType(FLOAT32)
FLOAT64_T = ColumnType(FLOAT64)
DATE_T = ColumnType(DATE)
TIMESTAMP_T = ColumnType(TIMESTAMP)
TIMESTAMPTZ_T = ColumnType(TIMESTAMPTZ)
TIME_T = ColumnType(TIME)
INTERVAL_T = ColumnType(INTERVAL)
TEXT_T = ColumnType(TEXT)
UUID_T = ColumnType(UUID)
BYTEA_T = ColumnType(BYTEA)
SKETCH_T = ColumnType(SKETCH)


def array_t(elem: str = "text") -> ColumnType:
    return ColumnType(ARRAY, elem=elem)


def decimal_t(precision: int, scale: int) -> ColumnType:
    if scale < 0 or precision <= 0 or scale > precision:
        raise AnalysisError(f"invalid decimal({precision},{scale})")
    return ColumnType(DECIMAL, precision, scale)


_SQL_NAMES = {
    "bool": BOOL_T,
    "boolean": BOOL_T,
    "smallint": INT16_T,
    "int2": INT16_T,
    "int": INT32_T,
    "integer": INT32_T,
    "int4": INT32_T,
    "bigint": INT64_T,
    "int8": INT64_T,
    "real": FLOAT32_T,
    "float4": FLOAT32_T,
    "double": FLOAT64_T,
    "float8": FLOAT64_T,
    "date": DATE_T,
    "timestamp": TIMESTAMP_T,
    "timestamptz": TIMESTAMPTZ_T,
    "time": TIME_T,
    "interval": INTERVAL_T,
    "text": TEXT_T,
    "varchar": TEXT_T,
    "char": TEXT_T,
    "uuid": UUID_T,
    "bytea": BYTEA_T,
    "sketch": SKETCH_T,
}


def type_from_sql(name: str, args: Optional[list[int]] = None) -> ColumnType:
    name = name.lower()
    if name.endswith("[]"):
        elem = name[:-2].strip()
        if elem not in _SQL_NAMES and elem not in ("decimal", "numeric"):
            raise AnalysisError(f"unknown array element type: {elem}")
        return array_t(elem)
    if name in ("decimal", "numeric"):
        if not args:
            # NUMERIC without precision: default a wide fixed-point
            return decimal_t(18, 4)
        if len(args) == 1:
            return decimal_t(args[0], 0)
        return decimal_t(args[0], args[1])
    if name in ("double",) and args is None:
        return FLOAT64_T
    t = _SQL_NAMES.get(name)
    if t is None:
        raise AnalysisError(f"unknown type name: {name}")
    return t


# ---- arithmetic result typing ------------------------------------------

def common_super_type(a: ColumnType, b: ColumnType) -> ColumnType:
    """Result type for +,-,* style binary arithmetic and for comparisons'
    operand alignment.  Mirrors (simplified) PostgreSQL numeric promotion."""
    if a == b:
        return a
    if a.is_float or b.is_float:
        return FLOAT64_T
    if a.is_decimal or b.is_decimal:
        # int op decimal -> decimal with the larger scale
        sa = a.scale if a.is_decimal else 0
        sb = b.scale if b.is_decimal else 0
        return decimal_t(38, max(sa, sb))
    if a.is_integer and b.is_integer:
        return INT64_T
    if a.kind == b.kind:
        return a
    if {a.kind, b.kind} == {DATE, TIMESTAMP}:
        return TIMESTAMP_T
    raise AnalysisError(f"no common type for {a} and {b}")


def arith_result_type(op: str, a: ColumnType, b: ColumnType) -> ColumnType:
    if not (a.is_numeric and b.is_numeric):
        # allow date +/- int (day arithmetic)
        if op in ("+", "-") and a.kind == DATE and b.is_integer:
            return DATE_T
        # timestamp[tz]/interval arithmetic: both sides are microsecond
        # int64 physicals, so device addition is exact
        ts_kinds = (TIMESTAMP, TIMESTAMPTZ)
        if op in ("+", "-") and a.kind in ts_kinds and b.kind == INTERVAL:
            return ColumnType(a.kind)
        if op == "+" and a.kind == INTERVAL and b.kind in ts_kinds:
            return ColumnType(b.kind)
        if op == "-" and a.kind in ts_kinds and b.kind == a.kind:
            return INTERVAL_T
        if op in ("+", "-") and a.kind == INTERVAL and b.kind == INTERVAL:
            return INTERVAL_T
        if op == "*" and ((a.kind == INTERVAL and b.is_integer)
                          or (a.is_integer and b.kind == INTERVAL)):
            return INTERVAL_T
        raise AnalysisError(f"operator {op} not defined for {a}, {b}")
    if op == "/":
        # exact decimal division is finalized on host; device computes
        # float64 (documented divergence from PG NUMERIC division)
        if a.is_float or b.is_float or a.is_decimal or b.is_decimal:
            return FLOAT64_T
        return INT64_T  # SQL integer division truncates
    if op == "%":
        if a.is_integer and b.is_integer:
            return INT64_T
        raise AnalysisError("% requires integers")
    if op == "*" and (a.is_decimal or b.is_decimal) and not (a.is_float or b.is_float):
        sa = a.scale if a.is_decimal else 0
        sb = b.scale if b.is_decimal else 0
        return decimal_t(38, sa + sb)  # scales add on multiply
    return common_super_type(a, b)
