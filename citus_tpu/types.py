"""Column type system.

Design goal: every SQL type maps to a fixed-width *physical* representation
that a TPU kernel can process, with exact (bit-identical) aggregate
semantics for the types the reference's analytics path cares about
(reference: the NUMERIC/aggregate machinery used by
multi_logical_optimizer.c's worker/master aggregate split).

Physical encodings:

=============  =====================  ============================
SQL type       storage dtype          semantics
=============  =====================  ============================
BOOL           int8                   0/1
SMALLINT       int16                  widened to int64 on device
INT/INTEGER    int32                  widened to int64 on device
BIGINT         int64
REAL           float32
DOUBLE         float64
DECIMAL(p,s)   int64                  value * 10**s (exact fixed point)
DATE           int32                  days since 1970-01-01
TIMESTAMP      int64                  microseconds since epoch
TEXT/VARCHAR   int32                  table-global dictionary id
=============  =====================  ============================

Exactness: DECIMAL arithmetic and SUM/AVG run on scaled int64, so results
are bit-identical regardless of reduction order — this is what lets the
per-shard partial aggregate + ``psum`` combine reproduce the single-node
answer exactly (the reference gets the same property from PostgreSQL's
arbitrary-precision NUMERIC).

Nulls are carried in a separate validity bitmap (storage) / bool mask
(device); the value slot under a null is 0.
"""

from __future__ import annotations

import datetime
import decimal
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from citus_tpu.errors import AnalysisError

# type kinds
BOOL = "bool"
INT16 = "int16"
INT32 = "int32"
INT64 = "int64"
FLOAT32 = "float32"
FLOAT64 = "float64"
DECIMAL = "decimal"
DATE = "date"
TIMESTAMP = "timestamp"
TEXT = "text"

_EPOCH_DATE = datetime.date(1970, 1, 1)

_STORAGE_DTYPES = {
    BOOL: np.int8,
    INT16: np.int16,
    INT32: np.int32,
    INT64: np.int64,
    FLOAT32: np.float32,
    FLOAT64: np.float64,
    DECIMAL: np.int64,
    DATE: np.int32,
    TIMESTAMP: np.int64,
    TEXT: np.int32,
}

# dtype the expression/aggregate kernels compute in
_DEVICE_DTYPES = {
    BOOL: np.int32,
    INT16: np.int64,
    INT32: np.int64,
    INT64: np.int64,
    FLOAT32: np.float32,
    FLOAT64: np.float64,
    DECIMAL: np.int64,
    DATE: np.int32,
    TIMESTAMP: np.int64,
    TEXT: np.int32,
}


@dataclass(frozen=True)
class ColumnType:
    kind: str
    precision: int = 0  # DECIMAL only
    scale: int = 0      # DECIMAL only

    # ---- classification ------------------------------------------------
    @property
    def is_integer(self) -> bool:
        return self.kind in (INT16, INT32, INT64)

    @property
    def is_float(self) -> bool:
        return self.kind in (FLOAT32, FLOAT64)

    @property
    def is_decimal(self) -> bool:
        return self.kind == DECIMAL

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_float or self.is_decimal

    @property
    def is_text(self) -> bool:
        return self.kind == TEXT

    @property
    def is_orderable_physical(self) -> bool:
        """True when physical-value order == logical order (everything but
        TEXT, whose dictionary ids are assigned in insertion order)."""
        return self.kind != TEXT

    # ---- dtypes --------------------------------------------------------
    @property
    def storage_dtype(self) -> np.dtype:
        return np.dtype(_STORAGE_DTYPES[self.kind])

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(_DEVICE_DTYPES[self.kind])

    # ---- value conversion ----------------------------------------------
    def to_physical(self, value: Any) -> int | float:
        """Python value -> physical scalar (dictionary ids handled by caller
        for TEXT)."""
        if value is None:
            return 0
        k = self.kind
        if k == BOOL:
            return 1 if value else 0
        if k in (INT16, INT32, INT64):
            return int(value)
        if k in (FLOAT32, FLOAT64):
            return float(value)
        if k == DECIMAL:
            d = value if isinstance(value, decimal.Decimal) else decimal.Decimal(str(value))
            q = d.scaleb(self.scale).to_integral_value(rounding=decimal.ROUND_HALF_UP)
            return int(q)
        if k == DATE:
            if isinstance(value, str):
                value = datetime.date.fromisoformat(value)
            return (value - _EPOCH_DATE).days
        if k == TIMESTAMP:
            if isinstance(value, str):
                value = datetime.datetime.fromisoformat(value)
            # integer arithmetic: float .timestamp() loses sub-us precision
            delta = value.replace(tzinfo=None) - datetime.datetime(1970, 1, 1)
            return delta // datetime.timedelta(microseconds=1)
        raise AnalysisError(f"cannot convert value for type {self}")

    def from_physical(self, raw: int | float, null: bool = False) -> Any:
        """Physical scalar -> Python value (TEXT handled by caller)."""
        if null:
            return None
        k = self.kind
        if k == BOOL:
            return bool(raw)
        if k in (INT16, INT32, INT64):
            return int(raw)
        if k in (FLOAT32, FLOAT64):
            return float(raw)
        if k == DECIMAL:
            return decimal.Decimal(int(raw)).scaleb(-self.scale)
        if k == DATE:
            return _EPOCH_DATE + datetime.timedelta(days=int(raw))
        if k == TIMESTAMP:
            return datetime.datetime.fromtimestamp(raw / 1_000_000, tz=datetime.timezone.utc).replace(tzinfo=None)
        raise AnalysisError(f"cannot convert value for type {self}")

    def __str__(self) -> str:
        if self.kind == DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        return self.kind


# canonical singletons
BOOL_T = ColumnType(BOOL)
INT16_T = ColumnType(INT16)
INT32_T = ColumnType(INT32)
INT64_T = ColumnType(INT64)
FLOAT32_T = ColumnType(FLOAT32)
FLOAT64_T = ColumnType(FLOAT64)
DATE_T = ColumnType(DATE)
TIMESTAMP_T = ColumnType(TIMESTAMP)
TEXT_T = ColumnType(TEXT)


def decimal_t(precision: int, scale: int) -> ColumnType:
    if scale < 0 or precision <= 0 or scale > precision:
        raise AnalysisError(f"invalid decimal({precision},{scale})")
    return ColumnType(DECIMAL, precision, scale)


_SQL_NAMES = {
    "bool": BOOL_T,
    "boolean": BOOL_T,
    "smallint": INT16_T,
    "int2": INT16_T,
    "int": INT32_T,
    "integer": INT32_T,
    "int4": INT32_T,
    "bigint": INT64_T,
    "int8": INT64_T,
    "real": FLOAT32_T,
    "float4": FLOAT32_T,
    "double": FLOAT64_T,
    "float8": FLOAT64_T,
    "date": DATE_T,
    "timestamp": TIMESTAMP_T,
    "text": TEXT_T,
    "varchar": TEXT_T,
    "char": TEXT_T,
}


def type_from_sql(name: str, args: Optional[list[int]] = None) -> ColumnType:
    name = name.lower()
    if name in ("decimal", "numeric"):
        if not args:
            # NUMERIC without precision: default a wide fixed-point
            return decimal_t(18, 4)
        if len(args) == 1:
            return decimal_t(args[0], 0)
        return decimal_t(args[0], args[1])
    if name in ("double",) and args is None:
        return FLOAT64_T
    t = _SQL_NAMES.get(name)
    if t is None:
        raise AnalysisError(f"unknown type name: {name}")
    return t


# ---- arithmetic result typing ------------------------------------------

def common_super_type(a: ColumnType, b: ColumnType) -> ColumnType:
    """Result type for +,-,* style binary arithmetic and for comparisons'
    operand alignment.  Mirrors (simplified) PostgreSQL numeric promotion."""
    if a == b:
        return a
    if a.is_float or b.is_float:
        return FLOAT64_T
    if a.is_decimal or b.is_decimal:
        # int op decimal -> decimal with the larger scale
        sa = a.scale if a.is_decimal else 0
        sb = b.scale if b.is_decimal else 0
        return decimal_t(38, max(sa, sb))
    if a.is_integer and b.is_integer:
        return INT64_T
    if a.kind == b.kind:
        return a
    if {a.kind, b.kind} == {DATE, TIMESTAMP}:
        return TIMESTAMP_T
    raise AnalysisError(f"no common type for {a} and {b}")


def arith_result_type(op: str, a: ColumnType, b: ColumnType) -> ColumnType:
    if not (a.is_numeric and b.is_numeric):
        # allow date +/- int (day arithmetic)
        if op in ("+", "-") and a.kind == DATE and b.is_integer:
            return DATE_T
        raise AnalysisError(f"operator {op} not defined for {a}, {b}")
    if op == "/":
        # exact decimal division is finalized on host; device computes
        # float64 (documented divergence from PG NUMERIC division)
        if a.is_float or b.is_float or a.is_decimal or b.is_decimal:
            return FLOAT64_T
        return INT64_T  # SQL integer division truncates
    if op == "%":
        if a.is_integer and b.is_integer:
            return INT64_T
        raise AnalysisError("% requires integers")
    if op == "*" and (a.is_decimal or b.is_decimal) and not (a.is_float or b.is_float):
        sa = a.scale if a.is_decimal else 0
        sb = b.scale if b.is_decimal else 0
        return decimal_t(38, sa + sb)  # scales add on multiply
    return common_super_type(a, b)
