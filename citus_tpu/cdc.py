"""Change data capture.

Reference: the CDC decoder wrapper (src/backend/distributed/cdc/
cdc_decoder.c) that rewrites logical-decoding changes from shard OIDs to
distributed-table OIDs and suppresses internal replication (shard moves)
via the DoNotReplicateId origin.

Here the change stream is written at commit time by the DML/ingest paths
(there is no WAL to decode): one JSONL stream per table, ordered by the
transaction's HLC timestamp.  Internal data movement (shard moves,
rebalances, VACUUM rewrites) bypasses the emit path entirely, giving the
same "changes once, at the distributed-table level" guarantee.

Stream hygiene (round 4): a sparse lsn->byte-offset index grows with the
stream so ``events(from_lsn)`` seeks instead of rescanning history
(O(new records), like a replication slot's confirmed_flush position);
``acknowledge()`` truncates records a consumer has confirmed, keeping
the stream bounded (the slot-advance / WAL-recycling analog).

Gated by ``enable_change_data_capture`` per cluster (reference GUC
citus.enable_change_data_capture).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator, Optional

#: a new index entry per this many appended stream bytes
INDEX_STRIDE_BYTES = 16384


class ChangeDataCapture:
    def __init__(self, data_dir: str, enabled: bool = False):
        self.dir = os.path.join(data_dir, "cdc")
        self.enabled = enabled
        self._mu = threading.Lock()
        # observability: bytes actually read by events() — tests assert
        # seek-reads stay O(new records)
        self.bytes_read = 0
        self._index_cache: dict[str, tuple] = {}  # table -> (sig, entries)
        # table -> (known stream size, max lsn of those bytes): the
        # running prefix-max recorded into index entries so seeks are
        # exact under HLC skew between emitters
        self._prefix_cache: dict[str, tuple[int, int]] = {}

    def _path(self, table: str) -> str:
        return os.path.join(self.dir, f"{table}.changes.jsonl")

    def _index_path(self, table: str) -> str:
        return os.path.join(self.dir, f"{table}.changes.idx.jsonl")

    def _ack_path(self, table: str) -> str:
        return os.path.join(self.dir, f"{table}.ack.json")

    # ------------------------------------------------------------ write
    def emit(self, table: str, op: str, lsn: int, *,
             rows: Optional[list] = None, count: Optional[int] = None,
             columns: Optional[list[str]] = None,
             force: bool = False) -> None:
        """op in {insert, delete, update}; lsn = HLC transaction clock.
        ``force`` bypasses the global switch (publication-covered tables
        capture even when enable_change_data_capture is off)."""
        if not (self.enabled or force):
            return
        os.makedirs(self.dir, exist_ok=True)
        rec = {"lsn": lsn, "op": op, "table": table}
        if columns is not None:
            rec["columns"] = columns
        if rows is not None:
            rec["rows"] = rows
            rec["count"] = len(rows)
        elif count is not None:
            rec["count"] = count
        from citus_tpu.utils.filelock import FileLock
        with self._mu, FileLock(os.path.join(self.dir, ".cdc.lock")):
            # the cross-process lock is shared with acknowledge(): an
            # append racing its read-rewrite-replace would otherwise be
            # dropped by the os.replace
            p = self._path(table)
            size = os.path.getsize(p) if os.path.exists(p) else 0
            entries = self._load_index_locked(table)
            pmax = self._prefix_max_locked(table, size, entries)
            last_off = entries[-1][1] if entries else -INDEX_STRIDE_BYTES
            if size - last_off >= INDEX_STRIDE_BYTES:
                # `size` is a record boundary (appends are whole lines
                # under the lock), so seeking there lands on a record.
                # pmax = max lsn over every byte before `offset`: the
                # seek can then PROVE all earlier records are consumed,
                # exactly, under any HLC skew between emitters
                with open(self._index_path(table), "a") as fh:
                    fh.write(json.dumps({"lsn": lsn, "offset": size,
                                         "pmax": pmax}) + "\n")
                self._index_cache.pop(table, None)
            with open(p, "a") as fh:
                line = json.dumps(rec, default=str) + "\n"
                fh.write(line)
                fh.flush()
            self._prefix_cache[table] = (size + len(line.encode()),
                                         max(pmax, lsn))

    def _prefix_max_locked(self, table: str, size: int, entries) -> int:
        """Max lsn over the stream's first ``size`` bytes.  Cached per
        table; foreign appends (another process emitting into the same
        stream) are folded in by scanning only the grown delta.  Called
        under the cdc lock."""
        known = self._prefix_cache.get(table)
        if known is not None and known[0] == size:
            return known[1]
        if known is not None and 0 < known[0] < size:
            m = max(known[1], self._range_max(table, known[0], size))
        elif size == 0:
            m = 0
        else:
            # cold start over an existing stream: index maxima cover the
            # prefix up to the last entry; scan the remaining (< one
            # stride) tail.  An old-format entry (no pmax) only knows
            # its own record's lsn, so fall back to a full scan once.
            m = 0
            start = 0
            if entries:
                if any(e[2] is None for e in entries):
                    start = 0
                else:
                    m = max(max(e[2] for e in entries),
                            max(e[0] for e in entries))
                    start = entries[-1][1]
            m = max(m, self._range_max(table, start, size))
        self._prefix_cache[table] = (size, m)
        return m

    def _range_max(self, table: str, start: int, end: int) -> int:
        m = 0
        with open(self._path(table), "rb") as fh:
            fh.seek(start)
            data = fh.read(end - start)
        for line in data.splitlines():
            line = line.strip()
            if line:
                try:
                    m = max(m, json.loads(line)["lsn"])
                except ValueError:
                    pass
        return m

    # ------------------------------------------------------------- read
    def _load_index_locked(self, table: str) -> list[tuple[int, int]]:
        """[(lsn, byte offset)] ascending; cached on (mtime, size)."""
        p = self._index_path(table)
        try:
            st = os.stat(p)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            return []
        cached = self._index_cache.get(table)
        if cached is not None and cached[0] == sig:
            return cached[1]
        entries = []
        with open(p) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    d = json.loads(line)
                    entries.append((d["lsn"], d["offset"], d.get("pmax")))
        self._index_cache[table] = (sig, entries)
        return entries

    def _seek_offset(self, table: str, from_lsn: int) -> int:
        """Largest indexed offset provably safe to resume from: an
        entry's ``pmax`` is the max lsn over every record before its
        offset, so pmax <= from_lsn guarantees nothing before the
        offset survives the ``lsn > from_lsn`` filter — exact under any
        HLC skew between concurrent emitters (a heuristic backstep is
        not: bursts compress arbitrarily many skewed records into one
        stride).  Old-format entries without pmax are never trusted."""
        if from_lsn <= 0:
            return 0
        # readers share the cache with emit(): the store below must not
        # race emit's invalidating pop
        with self._mu:
            entries = self._load_index_locked(table)
        best = 0
        for _lsn, off, pmax in entries:
            if pmax is None or pmax > from_lsn:
                break
            best = off
        return best

    def events(self, table: str, from_lsn: int = 0) -> Iterator[dict]:
        """Changes with lsn > from_lsn.  Seeks via the sparse index:
        reading the tail of a long-history stream costs O(new records),
        not O(history) — the confirmed_flush_lsn resume semantics of a
        logical replication slot."""
        p = self._path(table)
        if not os.path.exists(p):
            return
        start = self._seek_offset(table, from_lsn)
        with open(p) as fh:
            if start:
                fh.seek(start)
            for line in fh:
                self.bytes_read += len(line)
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec["lsn"] > from_lsn:
                    yield rec

    def has_stream(self, table: str) -> bool:
        """True when a change stream exists for the table — shard moves
        use it to pick their catch-up lag measure (pending change
        records when a stream exists, bytes-copied otherwise)."""
        return os.path.exists(self._path(table))

    def pending_count(self, table: str, from_lsn: int) -> int:
        """Number of change records with lsn > from_lsn: the replication
        lag a shard move's catch-up loop compares against
        citus.shard_move_catchup_threshold.  Costs O(tail) via the
        sparse index, like events()."""
        return sum(1 for _ in self.events(table, from_lsn))

    def last_lsn(self, table: str) -> int:
        """Newest change lsn — tail-read, O(last records) not
        O(history).  The window grows backwards until it holds at least
        one complete record (a single bulk-ingest record can exceed any
        fixed window)."""
        p = self._path(table)
        try:
            size = os.path.getsize(p)
        except OSError:
            return 0
        if size == 0:
            return 0
        window = 1 << 16
        while True:
            tail = min(size, window)
            with open(p, "rb") as fh:
                fh.seek(size - tail)
                chunk = fh.read(tail)
            self.bytes_read += len(chunk)
            lines = chunk.splitlines()
            if tail < size:
                lines = lines[1:]  # first line of a partial window
            last = 0
            for line in lines:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                last = max(last, rec["lsn"])
            if last or tail == size:
                return last
            window *= 4

    # --------------------------------------------------------- rotation
    def acknowledge(self, table: str, upto_lsn: int) -> int:
        """Consumer confirmation: drop records with lsn <= upto_lsn and
        rebuild the index (slot advance + WAL recycling).  Returns the
        number of records truncated."""
        p = self._path(table)
        if not os.path.exists(p):
            return 0
        from citus_tpu.utils.filelock import FileLock
        with self._mu, FileLock(os.path.join(self.dir, ".cdc.lock")):
            kept, dropped = [], 0
            with open(p) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    if json.loads(line)["lsn"] <= upto_lsn:
                        dropped += 1
                    else:
                        kept.append(line)
            # the confirmed position advances monotonically even when
            # nothing is truncated (consumer acked past the stream tail)
            if upto_lsn > self.acknowledged_lsn(table):
                with open(self._ack_path(table), "w") as fh:
                    json.dump({"acknowledged_lsn": upto_lsn}, fh)
            if not dropped:
                return 0
            tmp = p + ".tmp"
            idx_tmp = self._index_path(table) + ".tmp"
            off = 0
            running_max = 0
            with open(tmp, "w") as fh, open(idx_tmp, "w") as ix:
                last_indexed = -INDEX_STRIDE_BYTES
                for line in kept:
                    if off - last_indexed >= INDEX_STRIDE_BYTES:
                        ix.write(json.dumps(
                            {"lsn": json.loads(line)["lsn"],
                             "offset": off, "pmax": running_max}) + "\n")
                        last_indexed = off
                    fh.write(line + "\n")
                    off += len(line.encode()) + 1
                    running_max = max(running_max,
                                      json.loads(line)["lsn"])
            os.replace(tmp, p)
            os.replace(idx_tmp, self._index_path(table))
            self._index_cache.pop(table, None)
            self._prefix_cache[table] = (off, running_max)
            return dropped

    def acknowledged_lsn(self, table: str) -> int:
        try:
            with open(self._ack_path(table)) as fh:
                return json.load(fh)["acknowledged_lsn"]
        except (OSError, ValueError, KeyError):
            return 0
