"""Change data capture.

Reference: the CDC decoder wrapper (src/backend/distributed/cdc/
cdc_decoder.c) that rewrites logical-decoding changes from shard OIDs to
distributed-table OIDs and suppresses internal replication (shard moves)
via the DoNotReplicateId origin.

Here the change stream is written at commit time by the DML/ingest paths
(there is no WAL to decode): one JSONL stream per table, ordered by the
transaction's HLC timestamp.  Internal data movement (shard moves,
rebalances, VACUUM rewrites) bypasses the emit path entirely, giving the
same "changes once, at the distributed-table level" guarantee.

Gated by ``enable_change_data_capture`` per cluster (reference GUC
citus.enable_change_data_capture).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator, Optional


class ChangeDataCapture:
    def __init__(self, data_dir: str, enabled: bool = False):
        self.dir = os.path.join(data_dir, "cdc")
        self.enabled = enabled
        self._mu = threading.Lock()

    def _path(self, table: str) -> str:
        return os.path.join(self.dir, f"{table}.changes.jsonl")

    def emit(self, table: str, op: str, lsn: int, *,
             rows: Optional[list] = None, count: Optional[int] = None,
             columns: Optional[list[str]] = None) -> None:
        """op in {insert, delete, update}; lsn = HLC transaction clock."""
        if not self.enabled:
            return
        os.makedirs(self.dir, exist_ok=True)
        rec = {"lsn": lsn, "op": op, "table": table}
        if columns is not None:
            rec["columns"] = columns
        if rows is not None:
            rec["rows"] = rows
            rec["count"] = len(rows)
        elif count is not None:
            rec["count"] = count
        with self._mu:
            with open(self._path(table), "a") as fh:
                fh.write(json.dumps(rec, default=str) + "\n")
                fh.flush()

    def events(self, table: str, from_lsn: int = 0) -> Iterator[dict]:
        p = self._path(table)
        if not os.path.exists(p):
            return
        with open(p) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec["lsn"] > from_lsn:
                    yield rec

    def last_lsn(self, table: str) -> int:
        last = 0
        for rec in self.events(table):
            last = max(last, rec["lsn"])
        return last
