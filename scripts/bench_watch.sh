#!/bin/sh
# Probe the TPU tunnel on a ~14 min cadence all round (honest rc in
# TUNNEL_PROBES.log); the moment a probe sees DEVICES, capture a fresh
# bench (once), refreshing .bench_last_good.json via bench.py itself.
cd /root/repo || exit 1
N=${WATCH_ITERS:-45}
i=0
while [ "$i" -lt "$N" ]; do
    i=$((i + 1))
    sh scripts/tunnel_probe.sh
    LAST=$(tail -1 TUNNEL_PROBES.log)
    case "$LAST" in
    *"rc=0"*DEVICES*)
        if [ ! -f .bench_fresh_r11 ]; then
            BENCH_PROBE_TIMEOUT_S=240 BENCH_RETRY_DELAY_S=30 \
                BENCH_JOIN=1 BENCH_SWEEP=1 \
                python bench.py > .bench_auto.out 2> .bench_auto.err
            # a fresh (non-fallback) record carries no "stale" marker
            if [ -s .bench_auto.out ] && ! grep -q '"stale": true' .bench_auto.out; then
                touch .bench_fresh_r11
            fi
        fi
        ;;
    esac
    sleep 840
done
