#!/bin/sh
# Probe the TPU tunnel on a ~14 min cadence all round (honest rc in
# TUNNEL_PROBES.log); the moment a probe sees DEVICES, capture a fresh
# bench (once), refreshing .bench_last_good.json via bench.py itself.
#
# Wedge detection: two consecutive rc=124 probes mean the tunnel is
# wedged, not merely busy — append a structured {"event":"tunnel_wedged"}
# line to TUNNEL_PROBES.log and arm the marker file the flight
# recorder's health engine turns into a device_probe_wedged event /
# Prometheus gauge, instead of silently replaying the stale number.
# A later healthy probe (any rc=0) disarms the marker: requiring the
# DEVICES substring too silently skipped captures whenever the probe's
# stdout formatting drifted — rc is the authority, the substring is not.
cd /root/repo || exit 1
N=${WATCH_ITERS:-45}
WEDGE_MARKER=${CITUS_WEDGE_MARKER:-.tunnel_wedged}
i=0
WEDGED_STREAK=0
while [ "$i" -lt "$N" ]; do
    i=$((i + 1))
    sh scripts/tunnel_probe.sh
    LAST=$(tail -1 TUNNEL_PROBES.log)
    case "$LAST" in
    *"rc=0"*)
        WEDGED_STREAK=0
        rm -f "$WEDGE_MARKER"
        if [ ! -f .bench_fresh_r19 ]; then
            BENCH_PROBE_TIMEOUT_S=240 BENCH_RETRY_DELAY_S=30 \
                BENCH_JOIN=1 BENCH_SWEEP=1 \
                python bench.py > .bench_auto.out 2> .bench_auto.err
            # a fresh (non-fallback) record carries no "stale" marker
            if [ -s .bench_auto.out ] && ! grep -q '"stale": true' .bench_auto.out; then
                touch .bench_fresh_r19
            fi
        fi
        ;;
    *"rc=124"*)
        WEDGED_STREAK=$((WEDGED_STREAK + 1))
        if [ "$WEDGED_STREAK" -ge 2 ]; then
            TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
            EV="{\"event\":\"tunnel_wedged\",\"ts\":\"$TS\",\"consecutive_rc124\":$WEDGED_STREAK}"
            echo "$EV" >> TUNNEL_PROBES.log
            printf '%s\n' "$EV" > "$WEDGE_MARKER"
        fi
        ;;
    *"rc=skip"*)
        # bench holds the device: says nothing about tunnel health
        ;;
    *)
        WEDGED_STREAK=0
        ;;
    esac
    sleep 840
done
