#!/bin/sh
# appends one line per probe attempt to TUNNEL_PROBES.log
TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
# the axon tunnel serializes clients: probing while a bench run owns the
# device would block (or wedge) both — record the skip instead
if pgrep -f "python bench.py" >/dev/null 2>&1; then
    echo "$TS rc=skip bench.py holds the device (probe skipped)" >> /root/repo/TUNNEL_PROBES.log
    exit 0
fi
OUT=$(timeout 90 python -c "import jax; d=jax.devices(); print('DEVICES', len(d), d[0].platform)" 2>&1 | tail -1)
RC=$?
echo "$TS rc=$RC $OUT" >> /root/repo/TUNNEL_PROBES.log
