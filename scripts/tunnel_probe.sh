#!/bin/sh
# appends one line per probe attempt to TUNNEL_PROBES.log
TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
# the axon tunnel serializes clients: probing while a bench run owns the
# device would starve both (single-core box) — record the skip instead
if pgrep -f "python bench.py" >/dev/null 2>&1; then
    echo "$TS rc=skip bench.py holds the device (probe skipped)" >> /root/repo/TUNNEL_PROBES.log
    exit 0
fi
# NOTE: rc must be python's, not a pipeline tail's; a timed-out probe
# still emits the axon-plugin WARNING on stderr, so only an explicit
# DEVICES line counts as success
OUT=$(timeout "${PROBE_TIMEOUT_S:-120}" python -c "import jax; d=jax.devices(); print('DEVICES', len(d), d[0].platform)" 2>&1)
RC=$?
LAST=$(printf '%s\n' "$OUT" | grep DEVICES | tail -1)
[ -n "$LAST" ] || LAST=$(printf '%s\n' "$OUT" | tail -1)
echo "$TS rc=$RC $LAST" >> /root/repo/TUNNEL_PROBES.log
# forensics: the one-line summary drops the axon-plugin stack trace that
# explains WHY a probe failed; keep every probe's complete output in a
# companion log (indented so probes stay visually delimited) without
# breaking the one-line-per-probe format the watcher's tail -1 parses
{
    echo "$TS rc=$RC full output:"
    printf '%s\n' "$OUT" | sed 's/^/    /'
} >> /root/repo/TUNNEL_PROBES.full.log
