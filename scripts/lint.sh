#!/usr/bin/env bash
# One-shot static-analysis entry point: AST-based lint over the whole
# package (tools/cituslint).  Exit 0 = clean tree, 1 = diagnostics.
#
#   scripts/lint.sh                 # lint citus_tpu with every rule
#   scripts/lint.sh --select LOCK01 # one rule
#   scripts/lint.sh --list-rules    # rule table
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.cituslint citus_tpu "$@"
