#!/usr/bin/env python
"""VERDICT round-2 item #10: time the Pallas segment-reduction kernels
against the XLA one-hot formulation on real hardware, at several (N, G),
and report which should be the default.

Run on the TPU (no env pinning) once the tunnel is healthy:
    python scripts/pallas_timing.py
Prints a table and a recommendation; results feed the use_pallas default.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    backend = devs[0].platform
    interpret = backend != "tpu"
    print(f"backend={backend} interpret={interpret}")

    from citus_tpu.ops.pallas_kernels import segment_sum_pallas

    def onehot_sum(gid, upd, G):
        onehot = gid[None, :] == jnp.arange(G, dtype=gid.dtype)[:, None]
        return jnp.sum(jnp.where(onehot, upd[None, :], jnp.int64(0)), axis=1)

    rows = []
    for N in (65536, 262144, 1048576):
        for G in (8, 64, 1024, 8192):
            rng = np.random.default_rng(1)
            gid = rng.integers(0, G, N).astype(np.int32)
            upd = rng.integers(0, 1000, N).astype(np.int64)
            ones = np.ones(N, bool)

            f_x = jax.jit(lambda g, u: onehot_sum(g, u, G))
            f_p = jax.jit(lambda g, u: segment_sum_pallas(
                g, u, jnp.ones_like(g, dtype=bool), G=G, interpret=interpret))

            a = np.asarray(f_x(gid, upd))
            b = np.asarray(f_p(gid, upd))
            assert np.array_equal(a, b), (N, G, "mismatch")

            def timeit(f):
                f(gid, upd).block_until_ready()
                t0 = time.perf_counter()
                for _ in range(5):
                    f(gid, upd).block_until_ready()
                return (time.perf_counter() - t0) / 5

            tx, tp = timeit(f_x), timeit(f_p)
            rows.append((N, G, tx * 1e3, tp * 1e3, tx / tp))
            print(f"N={N:>8} G={G:>5}  onehot={tx*1e3:8.3f}ms  "
                  f"pallas={tp*1e3:8.3f}ms  speedup={tx/tp:6.2f}x",
                  flush=True)

    wins = sum(1 for r in rows if r[4] > 1.1)
    print(f"\npallas wins {wins}/{len(rows)} configs (>1.1x)")
    print("recommendation:",
          "flip use_pallas default ON" if wins > len(rows) * 0.6
          else "keep use_pallas OFF (XLA one-hot is competitive)")


if __name__ == "__main__":
    main()
