#!/usr/bin/env python
"""Prometheus text-exposition exporter for a citus_tpu data directory.

Opens a Cluster over ``data_dir`` and either dumps the metrics text once
to stdout (default) or serves it on ``--port`` at ``/metrics`` until
interrupted — the minimal scrape target for a Prometheus job:

    python scripts/metrics_exporter.py /path/to/db             # one dump
    python scripts/metrics_exporter.py /path/to/db --port 9187 # serve
    python scripts/metrics_exporter.py /path/to/db --cluster   # fan-out

The default payload is exactly what ``SHOW citus.metrics`` / ``SELECT
citus_metrics()`` return in-process: StatCounters as counters, cache
occupancy as gauges, and per-query-family latency histograms
(citus_tpu/observability/export.py).  ``--cluster`` serves the
node-labeled fan-out text instead (``SELECT citus_cluster_metrics()``):
every live node's series tagged ``{node="N"}`` plus
``citus_node_unreachable`` markers.  Note that plain counters are
per-process — this exporter sees the activity of ITS cluster handle,
which is the normal embedded deployment (one process owns the data
dir); point it at a live workload by running it inside that process or
scraping SHOW citus.metrics through SQL instead.
"""

from __future__ import annotations

import argparse
import sys


def render_metrics(cl, cluster_wide: bool) -> str:
    if cluster_wide:
        from citus_tpu.observability.export import prometheus_cluster_text
        return prometheus_cluster_text(cl)
    from citus_tpu.observability.export import prometheus_text
    return prometheus_text(cl)


def make_server(cl, port: int, cluster_wide: bool = False,
                host: str = "0.0.0.0"):
    """Build (not run) the /metrics HTTP server — separable so tests
    can scrape a live port without spawning the script."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib naming)
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            body = render_metrics(cl, cluster_wide).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    return HTTPServer((host, port), Handler)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("data_dir", help="cluster data directory")
    ap.add_argument("--port", type=int, default=0,
                    help="serve /metrics on this port instead of a "
                         "one-shot stdout dump")
    ap.add_argument("--cluster", action="store_true",
                    help="serve the cluster-wide node-labeled fan-out "
                         "text (citus_cluster_metrics) instead of the "
                         "local process view")
    args = ap.parse_args(argv)

    from citus_tpu import Cluster

    # Failure semantics: dead nodes are a DEGRADED scrape, not a failed
    # one — the render itself folds them into citus_node_unreachable
    # markers (observability/export.py).  Only a total failure (cluster
    # won't open, render raises, port won't bind) exits non-zero.
    try:
        cl = Cluster(args.data_dir)
    except Exception as e:
        print(f"metrics_exporter: cannot open cluster: {e}",
              file=sys.stderr)
        return 1
    try:
        if not args.port:
            try:
                sys.stdout.write(render_metrics(cl, args.cluster))
            except Exception as e:
                print(f"metrics_exporter: render failed: {e}",
                      file=sys.stderr)
                return 1
            return 0

        try:
            srv = make_server(cl, args.port, cluster_wide=args.cluster)
        except OSError as e:
            print(f"metrics_exporter: cannot bind :{args.port}: {e}",
                  file=sys.stderr)
            return 1
        print(f"serving /metrics on :{srv.server_address[1]}",
              file=sys.stderr)
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            srv.server_close()
        return 0
    finally:
        cl.close()


if __name__ == "__main__":
    raise SystemExit(main())
