#!/bin/sh
# appends one line per probe attempt to TUNNEL_PROBES.log
TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
OUT=$(timeout 90 python -c "import jax; d=jax.devices(); print('DEVICES', len(d), d[0].platform)" 2>&1 | tail -1)
RC=$?
echo "$TS rc=$RC $OUT" >> /root/repo/TUNNEL_PROBES.log
